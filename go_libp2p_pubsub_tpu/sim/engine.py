"""The simulation engine: one jitted step = decay + heartbeat + traffic.

Composes the batched kernels into the per-tick transition the reference runs
per node per second (gossipsub.go:1320-1343 heartbeat timer, score.go:408-445
decay ticker, plus the continuous data plane):

    step: (state, key) -> state
      0. churn              (optional) edge down/up round, RemovePeer semantics
      1. publish            P scenario-chosen messages enter the network
      2. heartbeat          mesh maintenance + GRAFT/PRUNE exchange + gossip
                            peer selection (score decay applies INLINE at
                            every counter read/write site — there is no
                            standalone decay pass; see ops/score_ops
                            docstring, PERF_MODEL.md S5. Stored counters at
                            tick boundaries are bit-identical to the old
                            decay-pass ordering.)
      3. forward_tick       IWANT resolution, mesh forwarding hops, IHAVE emit

The Go router interleaves these nondeterministically across goroutines; the
engine fixes the canonical order above (SURVEY.md §7 "Order-sensitivity").

``run`` lax.scans the step for n_ticks entirely on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..ops.churn import churn_edges, churn_subscriptions
from ..ops.gater import gater_decay
from ..ops.heartbeat import HeartbeatOut, heartbeat
from ..ops.propagate import forward_tick, publish
from .config import SimConfig, TopicParams
from .state import NEVER, SimState, decode_state, encode_state


def choose_publishers(state: SimState, cfg: SimConfig, key: jax.Array
                      ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Default scenario: P random peers publish, each to a random topic it
    subscribes to (peers with no subscriptions fall back to topic 0, which
    only arises in custom scenarios). Under a plan with
    :class:`~.faults.StormWindow`\\ s, active windows re-skew the draw
    toward the hot publisher set (flash-crowd workload, sim/faults.py) —
    the storm split only exists for storm plans, so every other config
    keeps the exact historical RNG stream."""
    storms = cfg.fault_plan is not None and cfg.fault_plan.storms
    if storms:
        key, k_storm = jax.random.split(key)
    kp, kt = jax.random.split(key)
    p = cfg.publishers_per_tick
    peers = jax.random.randint(kp, (p,), 0, cfg.n_peers)
    sub = state.subscribed[peers]                       # [P, T]
    g = jax.random.gumbel(kt, sub.shape)
    topics = jnp.argmax(jnp.where(sub, g, -jnp.inf), axis=-1).astype(jnp.int32)
    if storms:
        from .faults import storm_publishers
        peers, topics = storm_publishers(state, cfg, peers, topics, k_storm)
    return peers, topics


def _iwant_answer_extras(state: SimState, cfg: SimConfig,
                         censor_bits: jnp.ndarray | None = None
                         ) -> list | None:
    """When the tick's exchanges ride a formulation that can carry extra
    word lanes, the IWANT answer-table gather (forward_tick step 1) is
    data-independent of the heartbeat — it reads only deliver_tick and
    malicious, which the heartbeat never writes — so it can share the
    heartbeat's FINAL exchange instead of paying its own
    serially-dependent pass (~13 serial sorts bound the sort-era tick;
    VERDICT r4 item 1). Two carriers exist: ``sort`` (extra lanes of the
    variadic sort) and ``mxu`` (extra word rows concatenated onto the
    bit-table, fetched by the same two-level take — the MXU formulation
    that closes the mode's last serialized self-gather). Returns the
    [W, N] answer table to ride along, or None when the formulations
    don't line up (scalar/rows/pallas exchanges, or the fused resolve
    kernel, which gathers in VMEM)."""
    from ..ops.bits import pack_words
    from ..ops.hopkernel import resolve_hop_mode
    from ..ops.permgather import resolve_edge_packed_mode
    from ..sim.state import NEVER as _NEVER

    n, t, k = state.mesh.shape
    w = (cfg.msg_window + 31) // 32
    if resolve_hop_mode(cfg.hop_mode, cfg, w, n, k) in ("pallas",
                                                        "pallas-mxu"):
        return None                  # fused resolve kernel gathers in VMEM
    if resolve_edge_packed_mode(cfg.edge_gather_mode, n, k, 2 * t,
                                extra_w=w) not in ("sort", "mxu"):
        return None
    answer_bits = jnp.where(state.malicious[None, :], jnp.uint32(0),
                            pack_words(state.deliver_tick < _NEVER))
    if censor_bits is not None:
        # censors withhold the victim's messages from their answer table
        # (sim/faults.py censor_word_mask) — the SAME mask forward_tick
        # applies on its own answer path, so the ride-along is identical
        answer_bits = answer_bits & ~censor_bits
    return [answer_bits]


def step(state: SimState, cfg: SimConfig, tp: TopicParams,
         key: jax.Array) -> SimState:
    # the scan carry travels in the STORED layout (sim/state.py codec
    # tables): decode to the f32/i32 compute layout here, encode on the
    # way out — both identities under state_precision="f32", so every op
    # below sees the historical types under either precision
    state = decode_state(state, cfg)
    if cfg.fault_plan is not None:
        # the fault pass opens the tick: partition/outage transitions
        # (RemovePeer down, reconnect up) plus this tick's link/corruption
        # draws (sim/faults.py). The pre-split keeps plan-free configs on
        # the exact historical RNG stream.
        from .faults import apply_faults
        key, k_fault = jax.random.split(key)
        state, fault = apply_faults(state, cfg, tp, k_fault)
    else:
        fault = None
    k_pub, k_hb, k_fwd, k_churn, k_ign, k_sub = jax.random.split(key, 6)
    if cfg.sub_leave_prob > 0.0 or cfg.sub_join_prob > 0.0:
        state = churn_subscriptions(state, cfg, tp, k_sub)
    peers, topics = choose_publishers(state, cfg, k_pub)
    if fault is not None and fault.corrupt is not None:
        # effective corruption: draws landing on malicious publishers
        # corrupt nothing (their messages are invalid already), so the
        # FAULT_CORRUPT bit reflects what actually fired
        from .invariants import FAULT_CORRUPT
        corrupt_eff = fault.corrupt & ~state.malicious[peers]
        fault = fault._replace(
            corrupt=corrupt_eff,
            injected=fault.injected | jnp.where(
                jnp.any(corrupt_eff), jnp.uint32(FAULT_CORRUPT),
                jnp.uint32(0)))
    state = publish(state, cfg, peers, topics, k_ign,
                    corrupt=fault.corrupt if fault is not None else None)
    if cfg.fault_plan is not None:
        # the censor word mask reads msg_publisher, so it must be built
        # AFTER publish — the victim's brand-new messages are censored
        # the tick they appear (sim/faults.py)
        from .faults import censor_word_mask
        censor_bits = censor_word_mask(state, cfg)
    else:
        censor_bits = None
    if cfg.gater_enabled:
        state = gater_decay(state, cfg)
    if cfg.router == "gossipsub":
        hb = heartbeat(state, cfg, tp, k_hb,
                       extra_words=_iwant_answer_extras(
                           state, cfg, censor_bits=censor_bits))
    else:
        # floodsub/randomsub run NO heartbeat: no mesh maintenance, no
        # gossip, no scoring (floodsub.go/randomsub.go define none of it)
        n, t, k = state.mesh.shape
        hb = HeartbeatOut(state=state,
                          scores=jnp.zeros((n, k), jnp.float32),
                          scores_all=jnp.zeros((n, k), jnp.float32),
                          inc_gossip=jnp.zeros((n, t, k), bool),
                          fwd_send=jnp.zeros((n, t, k), bool))
    state = forward_tick(hb.state, cfg, tp, hb.inc_gossip, hb.scores, k_fwd,
                         fwd_send=hb.fwd_send if cfg.router == "gossipsub"
                         else None,
                         answers_k=hb.extra_routed[0]
                         if hb.extra_routed else None,
                         link_ok=fault.link_ok if fault is not None else None,
                         dup_edges=fault.dup_edges
                         if fault is not None else None,
                         censor_bits=censor_bits)
    if cfg.churn_disconnect_prob > 0.0:
        # connection churn closes the tick, reusing the heartbeat's score
        # cache (its unmasked variant) for the PX reconnect gate — one
        # compute_scores per tick, as the reference reuses its cache within
        # a heartbeat (gossipsub.go:1375-1381)
        state = churn_edges(state, cfg, tp, k_churn, scores_all=hb.scores_all,
                            forbid_up=fault.want_down
                            if fault is not None else None)
    from ..parallel.kernel_context import drain_halo_overflow
    notes = drain_halo_overflow()
    if notes:
        # halo-route bucket overflows this tick (parallel/halo.py capacity
        # rule): the counter makes a poisoned run self-identifying
        state = state._replace(
            halo_overflow=state.halo_overflow + sum(notes))
    if cfg.invariant_mode != "off":
        # the sentinel closes the tick: injected-fault bits + invariant
        # violations OR into the sticky flag word (sim/invariants.py);
        # "raise" additionally escalates via checkify (run_checked)
        from .invariants import record_flags
        state = record_flags(state, cfg,
                             injected=fault.injected
                             if fault is not None else None)
    return encode_state(state._replace(tick=state.tick + 1), cfg)


def _run_keys_impl(state: SimState, cfg: SimConfig, tp: TopicParams,
                   keys: jax.Array, telemetry: bool = False):
    """Advance one tick per row of ``keys`` on device — the chunkable core
    of ``run``. ``run`` pre-splits ONE master key into per-tick keys and
    scans them all; a caller that performs the same split and scans any
    contiguous windows of the key array (sim/supervisor.py chunked
    execution) lands on the bit-identical trajectory, because the per-tick
    key sequence — the only thing the scan consumes besides the carried
    state — is unchanged.

    ``telemetry=True`` (static) is the streaming-telemetry lane
    (sim/telemetry.py): the scan additionally stacks one per-tick
    :class:`~.telemetry.HealthRecord` — the device-side reduction, so
    only ``[C]``-stacked aggregates ever leave the chip — and the return
    becomes ``(state, HealthRecord)``. The carried state math is
    UNCHANGED: telemetry reads the post-step state, it never writes."""
    from .telemetry import health_record

    def body(carry, k):
        nxt = step(carry, cfg, tp, k)
        return nxt, health_record(nxt, cfg, tp) if telemetry else None

    state, health = jax.lax.scan(body, state, keys)
    return (state, health) if telemetry else state


def _run_window_impl(state: SimState, cfg: SimConfig, tp: TopicParams,
                     key: jax.Array, n_ticks: int, telemetry: bool = False):
    """The ``key_schedule="fold_in"`` scan core: each tick's key is
    derived INSIDE the scan as ``jax.random.fold_in(master, state.tick)``
    — no host pre-split, no shipped ``[C, 2]`` key window (at 1M peers
    the window was real HBM and real PCIe). Because the per-tick key is a
    function of the master and the ABSOLUTE tick the carry holds, any
    chunking of a window — and any resume from a checkpointed tick — lands
    on the bit-identical trajectory by construction, with no key array to
    keep aligned."""
    from .telemetry import health_record

    def body(carry, _):
        k = jax.random.fold_in(key, carry.tick)
        nxt = step(carry, cfg, tp, k)
        return nxt, health_record(nxt, cfg, tp) if telemetry else None

    state, health = jax.lax.scan(body, state, None, length=n_ticks)
    return (state, health) if telemetry else state


def _run_impl(state: SimState, cfg: SimConfig, tp: TopicParams,
              key: jax.Array, n_ticks: int) -> SimState:
    """Advance the whole network ``n_ticks`` heartbeats on device."""
    if cfg.key_schedule == "fold_in":
        return _run_window_impl(state, cfg, tp, key, n_ticks)
    if cfg.key_schedule != "host":
        raise ValueError(f"unknown key_schedule {cfg.key_schedule!r}; "
                         "expected 'host' or 'fold_in'")
    return _run_keys_impl(state, cfg, tp, jax.random.split(key, n_ticks))


def window_keys(cfg: SimConfig, key: jax.Array, start_tick: int,
                lo: int, hi: int, n_ticks: int) -> jax.Array:
    """The per-tick keys a run of ``n_ticks`` from ``start_tick`` consumes
    for its run-relative window ``[lo, hi)`` — the schedule-aware form the
    supervisor's crash dumps and traced/checkified chunk paths use.
    Under "host" this is a contiguous slice of the ONE master pre-split
    (``run``'s exact discipline); under "fold_in" the keys are folds of
    the ABSOLUTE tick numbers, materialized here only because the caller
    needs them on host (crash.json) or as explicit scan rows."""
    if cfg.key_schedule == "fold_in":
        ticks = jnp.arange(start_tick + lo, start_tick + hi)
        return jax.vmap(lambda t: jax.random.fold_in(key, t))(ticks)
    return jax.random.split(key, n_ticks)[lo:hi]


run = jax.jit(_run_impl, static_argnames=("cfg", "n_ticks"))
# the hot benchmarking path: donating the input state halves peak state
# memory (in-place XLA aliasing); callers must not reuse the argument
run_donated = jax.jit(_run_impl, static_argnames=("cfg", "n_ticks"),
                      donate_argnums=(0,))

# explicit per-tick keys (the supervisor's chunk unit; n_ticks is carried
# by keys.shape[0], a jit shape dimension rather than a static argument).
# telemetry is a static lane flag: the default program is byte-identical
# to the historical one, telemetry=True returns (state, HealthRecord)
run_keys = jax.jit(_run_keys_impl, static_argnames=("cfg", "telemetry"))
# donated flavor: the async supervisor pipeline owns its carry chain and
# donates chunk inputs it will never reuse (parallel/compile_plan.py
# decides which chunks those are; anchors and boundary states stay
# undonated so retries and off-path checkpoint fetches keep a live input)
run_keys_donated = jax.jit(_run_keys_impl,
                           static_argnames=("cfg", "telemetry"),
                           donate_argnums=(0,))
# the fold_in chunk unit: per-tick keys derive on device, so the chunk
# length is a STATIC argument instead of a key-array shape dimension
run_window = jax.jit(_run_window_impl,
                     static_argnames=("cfg", "n_ticks", "telemetry"))
run_window_donated = jax.jit(_run_window_impl,
                             static_argnames=("cfg", "n_ticks", "telemetry"),
                             donate_argnums=(0,))

step_jit = jax.jit(step, static_argnames=("cfg",))


def run_checked(state: SimState, cfg: SimConfig, tp: TopicParams,
                key: jax.Array, n_ticks: int) -> SimState:
    """``run`` with the invariant sentinel escalated to host exceptions:
    the whole scan is checkify-transformed, so ``invariant_mode="raise"``
    checks (sim/invariants.py) surface as a thrown ``JaxRuntimeError``
    naming the violation flags — the debugging mode for a poisoned run.
    Works (as a plain run) under ``"record"`` too; prefer ``run`` there."""
    from jax.experimental import checkify

    def f(state, tp, key):
        return _run_impl(state, cfg, tp, key, n_ticks)

    err, out = jax.jit(checkify.checkify(f, errors=checkify.user_checks))(
        state, tp, key)
    err.throw()
    return out


def run_checked_keys(state: SimState, cfg: SimConfig, tp: TopicParams,
                     keys: jax.Array, telemetry: bool = False):
    """``run_keys`` with the invariant sentinel escalated to host
    exceptions (see :func:`run_checked`) — the supervisor's execution path
    under ``invariant_mode="raise"`` and the replay path of
    ``scripts/replay_crash.py`` (which re-runs a crash dump's exact
    failing tick window from its recorded per-tick keys). ``telemetry``
    mirrors ``run_keys``' lane: ``(state, HealthRecord)`` when set."""
    from jax.experimental import checkify

    def f(state, tp, keys):
        return _run_keys_impl(state, cfg, tp, keys, telemetry=telemetry)

    err, out = jax.jit(checkify.checkify(f, errors=checkify.user_checks))(
        state, tp, keys)
    err.throw()
    return out


def mesh_degrees(state: SimState) -> jnp.ndarray:
    """[N, T] current mesh degree (for convergence checks). Accepts the
    compact storage layout too: a packed u32 mesh plane counts by
    popcount (pad bits are zero), no cfg needed."""
    if state.mesh.dtype == jnp.uint32:
        return jnp.sum(jax.lax.population_count(state.mesh),
                       axis=-1).astype(jnp.int32)
    return jnp.sum(state.mesh, axis=-1)


def delivery_fraction(state: SimState, cfg: SimConfig,
                      min_age_ticks: int = 0,
                      topic: int | None = None) -> jnp.ndarray:
    """Fraction of (subscribed peer, alive message) pairs delivered.

    ``min_age_ticks`` restricts the census to messages at least that many
    ticks old — the SETTLED window. The engine publishes every tick up to
    the end of a scan, and a message published on the final tick still has
    its gossip IHAVE->IWANT pull pending (a structural 1-tick delay,
    gossipsub.go:698-739), so saturation checks against a host-runtime run
    that got a settle period should pass min_age_ticks>=2 for a fair
    comparison. ``topic`` restricts the census to one topic: gossipsub can
    only deliver over edges BETWEEN subscribers, so a sparsely-subscribed
    topic whose induced subscriber subgraph is disconnected has a
    structural loss floor (tests/test_delivery_structural.py reachability
    oracle) that a saturation assert on a connected topic must not
    inherit (tests/test_cross_half_fuzz.py)."""
    age = state.tick - state.msg_publish_tick
    alive = (age < cfg.history_length) & (age >= min_age_ticks)
    t_m = jnp.clip(state.msg_topic, 0, cfg.n_topics - 1)
    should = state.subscribed[:, t_m] & alive[None, :] & (state.msg_topic >= 0)[None, :]
    if topic is not None:
        should = should & (state.msg_topic == topic)[None, :]
    from .state import unpack_have
    got = unpack_have(state, cfg.msg_window) & should
    return jnp.sum(got) / jnp.maximum(jnp.sum(should), 1)


def delivery_latency_ticks(state: SimState, cfg: SimConfig) -> jnp.ndarray:
    """Mean ticks from publish to delivery over delivered (peer, message)
    pairs in the live window — the propagation-latency metric of BASELINE
    config #5 (floodsub/randomsub/gossipsub sweep).

    The publisher's own zero-latency pair (publish() stamps its
    deliver_tick at the publish tick) is excluded by subtracting exactly
    one pair per live message; receivers' genuine same-tick deliveries
    still count as latency 0. Returns 0 when nothing but publishers
    delivered."""
    if state.deliver_tick.dtype != jnp.int32:   # compact storage layout
        state = decode_state(state, cfg)
    alive = (state.msg_publish_tick < NEVER) & \
        ((state.tick - state.msg_publish_tick) < cfg.history_length)
    dlv = (state.deliver_tick < NEVER) & alive[None, :]
    lat = (state.deliver_tick - state.msg_publish_tick[None, :]).astype(jnp.float32)
    n_msgs = jnp.sum(jnp.any(dlv, axis=0))      # one publisher pair each
    n_pairs = jnp.sum(dlv) - n_msgs
    return jnp.sum(jnp.where(dlv, lat, 0.0)) / jnp.maximum(n_pairs, 1)
