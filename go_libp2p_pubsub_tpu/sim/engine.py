"""The simulation engine: one jitted step = decay + heartbeat + traffic.

Composes the batched kernels into the per-tick transition the reference runs
per node per second (gossipsub.go:1320-1343 heartbeat timer, score.go:408-445
decay ticker, plus the continuous data plane):

    step: (state, key) -> state
      0. churn              (optional) edge down/up round, RemovePeer semantics
      1. publish            P scenario-chosen messages enter the network
      2. heartbeat          mesh maintenance + GRAFT/PRUNE exchange + gossip
                            peer selection (score decay applies INLINE at
                            every counter read/write site — there is no
                            standalone decay pass; see ops/score_ops
                            docstring, PERF_MODEL.md S5. Stored counters at
                            tick boundaries are bit-identical to the old
                            decay-pass ordering.)
      3. forward_tick       IWANT resolution, mesh forwarding hops, IHAVE emit

The Go router interleaves these nondeterministically across goroutines; the
engine fixes the canonical order above (SURVEY.md §7 "Order-sensitivity").

``run`` lax.scans the step for n_ticks entirely on device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..ops.churn import churn_edges, churn_subscriptions
from ..ops.gater import gater_decay
from ..ops.heartbeat import HeartbeatOut, heartbeat
from ..ops.propagate import forward_tick, publish
from .config import SimConfig, TopicParams
from .state import NEVER, SimState


def choose_publishers(state: SimState, cfg: SimConfig, key: jax.Array
                      ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Default scenario: P random peers publish, each to a random topic it
    subscribes to (peers with no subscriptions fall back to topic 0, which
    only arises in custom scenarios)."""
    kp, kt = jax.random.split(key)
    p = cfg.publishers_per_tick
    peers = jax.random.randint(kp, (p,), 0, cfg.n_peers)
    sub = state.subscribed[peers]                       # [P, T]
    g = jax.random.gumbel(kt, sub.shape)
    topics = jnp.argmax(jnp.where(sub, g, -jnp.inf), axis=-1).astype(jnp.int32)
    return peers, topics


def _iwant_answer_extras(state: SimState, cfg: SimConfig) -> list | None:
    """When the tick's exchanges ride the sort-permute formulation, the
    IWANT answer-table gather (forward_tick step 1) is data-independent of
    the heartbeat — it reads only deliver_tick and malicious, which the
    heartbeat never writes — so it can share the heartbeat's FINAL
    exchange's variadic sort instead of paying its own serially-dependent
    comparator pass (~13 serial sorts bound the sort-era tick; VERDICT r4
    item 1). Returns the [W, N] answer table to ride along, or None when
    the formulations don't line up (non-sort modes — mxu included: the
    two-level take gathers its own answer table — or the fused resolve
    kernel)."""
    from ..ops.bits import pack_words
    from ..ops.hopkernel import resolve_hop_mode
    from ..ops.permgather import resolve_edge_packed_mode
    from ..sim.state import NEVER as _NEVER

    n, t, k = state.mesh.shape
    w = (cfg.msg_window + 31) // 32
    if resolve_hop_mode(cfg.hop_mode, cfg, w, n, k) in ("pallas",
                                                        "pallas-mxu"):
        return None                  # fused resolve kernel gathers in VMEM
    if resolve_edge_packed_mode(cfg.edge_gather_mode, n, k, 2 * t) != "sort":
        return None
    answer_bits = jnp.where(state.malicious[None, :], jnp.uint32(0),
                            pack_words(state.deliver_tick < _NEVER))
    return [answer_bits]


def step(state: SimState, cfg: SimConfig, tp: TopicParams,
         key: jax.Array) -> SimState:
    k_pub, k_hb, k_fwd, k_churn, k_ign, k_sub = jax.random.split(key, 6)
    if cfg.sub_leave_prob > 0.0 or cfg.sub_join_prob > 0.0:
        state = churn_subscriptions(state, cfg, tp, k_sub)
    peers, topics = choose_publishers(state, cfg, k_pub)
    state = publish(state, cfg, peers, topics, k_ign)
    if cfg.gater_enabled:
        state = gater_decay(state, cfg)
    if cfg.router == "gossipsub":
        hb = heartbeat(state, cfg, tp, k_hb,
                       extra_words=_iwant_answer_extras(state, cfg))
    else:
        # floodsub/randomsub run NO heartbeat: no mesh maintenance, no
        # gossip, no scoring (floodsub.go/randomsub.go define none of it)
        n, t, k = state.mesh.shape
        hb = HeartbeatOut(state=state,
                          scores=jnp.zeros((n, k), jnp.float32),
                          scores_all=jnp.zeros((n, k), jnp.float32),
                          inc_gossip=jnp.zeros((n, t, k), bool),
                          fwd_send=jnp.zeros((n, t, k), bool))
    state = forward_tick(hb.state, cfg, tp, hb.inc_gossip, hb.scores, k_fwd,
                         fwd_send=hb.fwd_send if cfg.router == "gossipsub"
                         else None,
                         answers_k=hb.extra_routed[0]
                         if hb.extra_routed else None)
    if cfg.churn_disconnect_prob > 0.0:
        # connection churn closes the tick, reusing the heartbeat's score
        # cache (its unmasked variant) for the PX reconnect gate — one
        # compute_scores per tick, as the reference reuses its cache within
        # a heartbeat (gossipsub.go:1375-1381)
        state = churn_edges(state, cfg, tp, k_churn, scores_all=hb.scores_all)
    from ..parallel.kernel_context import drain_halo_overflow
    notes = drain_halo_overflow()
    if notes:
        # halo-route bucket overflows this tick (parallel/halo.py capacity
        # rule): the counter makes a poisoned run self-identifying
        state = state._replace(
            halo_overflow=state.halo_overflow + sum(notes))
    return state._replace(tick=state.tick + 1)


def _run_impl(state: SimState, cfg: SimConfig, tp: TopicParams,
              key: jax.Array, n_ticks: int) -> SimState:
    """Advance the whole network ``n_ticks`` heartbeats on device."""

    def body(carry, k):
        return step(carry, cfg, tp, k), None

    keys = jax.random.split(key, n_ticks)
    state, _ = jax.lax.scan(body, state, keys)
    return state


run = jax.jit(_run_impl, static_argnames=("cfg", "n_ticks"))
# the hot benchmarking path: donating the input state halves peak state
# memory (in-place XLA aliasing); callers must not reuse the argument
run_donated = jax.jit(_run_impl, static_argnames=("cfg", "n_ticks"),
                      donate_argnums=(0,))

step_jit = jax.jit(step, static_argnames=("cfg",))


def mesh_degrees(state: SimState) -> jnp.ndarray:
    """[N, T] current mesh degree (for convergence checks)."""
    return jnp.sum(state.mesh, axis=-1)


def delivery_fraction(state: SimState, cfg: SimConfig) -> jnp.ndarray:
    """Fraction of (subscribed peer, alive message) pairs delivered."""
    alive = (state.tick - state.msg_publish_tick) < cfg.history_length
    t_m = jnp.clip(state.msg_topic, 0, cfg.n_topics - 1)
    should = state.subscribed[:, t_m] & alive[None, :] & (state.msg_topic >= 0)[None, :]
    got = state.have & should
    return jnp.sum(got) / jnp.maximum(jnp.sum(should), 1)


def delivery_latency_ticks(state: SimState, cfg: SimConfig) -> jnp.ndarray:
    """Mean ticks from publish to delivery over delivered (peer, message)
    pairs in the live window — the propagation-latency metric of BASELINE
    config #5 (floodsub/randomsub/gossipsub sweep).

    The publisher's own zero-latency pair (publish() stamps its
    deliver_tick at the publish tick) is excluded by subtracting exactly
    one pair per live message; receivers' genuine same-tick deliveries
    still count as latency 0. Returns 0 when nothing but publishers
    delivered."""
    alive = (state.msg_publish_tick < NEVER) & \
        ((state.tick - state.msg_publish_tick) < cfg.history_length)
    dlv = (state.deliver_tick < NEVER) & alive[None, :]
    lat = (state.deliver_tick - state.msg_publish_tick[None, :]).astype(jnp.float32)
    n_msgs = jnp.sum(jnp.any(dlv, axis=0))      # one publisher pair each
    n_pairs = jnp.sum(dlv) - n_msgs
    return jnp.sum(jnp.where(dlv, lat, 0.0)) / jnp.maximum(n_pairs, 1)
