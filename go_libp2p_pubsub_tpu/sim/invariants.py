"""In-scan invariant sentinel: the ``SimState.fault_flags`` bit word.

Generalizes the ad-hoc ``halo_overflow`` counter (the engine's only
runtime health signal before this module) into one named uint32 flag word
carried through the scan and surfaced with every metric line (bench.py)
and trace export (sim/trace_export.py run_traced) — a poisoned number can
never be cited silently, and every degraded run is self-identifying.

Two bit classes share the word:

- **injected-fault bits** (low byte): which :class:`sim.faults.FaultPlan`
  faults actually fired during the run. Expected nonzero under a plan;
  their exact set is checkable against the plan (tests/test_faults.py).
- **invariant-violation bits** (bits 8+): conditions that must NEVER hold
  in a healthy run, plan or no plan. Any of these set means the
  trajectory is suspect.

``SimConfig.invariant_mode`` picks the escalation:

- ``"record"`` (default): OR the flags into ``state.fault_flags`` each
  tick — a handful of fused min/max/any reductions over arrays the tick
  already touched (measured overhead in PERF_MODEL.md "Invariant
  sentinel").
- ``"raise"``: additionally ``jax.experimental.checkify.check`` that no
  violation bit is set; callers must run through
  :func:`sim.engine.run_checked` (or checkify the step themselves) and
  get a host-side exception naming the flags — the debugging mode.
- ``"off"``: no checks, no flag writes (the pre-sentinel program).
"""

from __future__ import annotations

import jax.numpy as jnp

from .config import SimConfig
from .state import NEVER, SimState

U32 = jnp.uint32

# --- injected-fault bits (sim/faults.py sets these). The low HALF-WORD
# belongs to injected faults: the adversary/workload plane (ISSUE 10)
# outgrew the original low byte, so violations moved from bits 8+ to bits
# 16+. Flag WORDS recorded before that move are not decodable under the
# new layout: their old bits 8-9 (nonfinite/negative-counter violations)
# land on FAULT_CENSOR/FAULT_WAVE and their higher violation bits read as
# unknown — do not interpret pre-move journals/checkpoints' numeric flags
# with post-move code (named constants keep all CODE correct).
# FLAGS_VERSION makes that refusal mechanical: writers (health-journal
# headers, crash-dump meta) stamp it, and decode_flags(flags,
# flags_version=...) refuses any other version BY NAME instead of
# silently misreading. v1 = the pre-move layout (violations at bits 8+);
# v2 = this layout ---
FLAGS_VERSION = 2
FAULT_LINK_DROP = 1 << 0     # >=1 link dropped a data plane this run
FAULT_LINK_DUP = 1 << 1      # >=1 link duplicated traffic
FAULT_PARTITION = 1 << 2     # a partition window was active
FAULT_OUTAGE = 1 << 3        # an outage window was active
FAULT_CORRUPT = 1 << 4       # >=1 honest publish was corrupted
FAULT_STORM = 1 << 5         # a flash-crowd publish storm window was active
FAULT_SLOWLINK = 1 << 6      # >=1 slow-link class stalled a live edge
FAULT_ECLIPSE = 1 << 7       # an eclipse window was active
FAULT_CENSOR = 1 << 8        # a censorship window was active
FAULT_WAVE = 1 << 9          # a diurnal churn wave's dark phase was active

# --- invariant-violation bits ---
FLAG_NONFINITE = 1 << 16     # NaN/Inf in a score counter / app score
FLAG_NEG_COUNTER = 1 << 17   # a monotone/decayed counter went negative
FLAG_MESH_DEAD_EDGE = 1 << 18  # mesh slot points at a down/absent edge
FLAG_GRAFT_IN_BACKOFF = 1 << 19  # edge grafted while its backoff was live
FLAG_SLOT_GARBAGE = 1 << 20  # slot/topic index out of range (packed-word
#                              tail-bit garbage decodes into this class)
FLAG_DELIVER_FUTURE = 1 << 21  # deliver_tick > tick, negative, or
#                                delivered-but-not-seen
FLAG_HALO_OVERFLOW = 1 << 22  # halo-route bucket overflow (counter > 0)

VIOLATION_MASK = 0xFFFF0000
INJECTED_MASK = 0x0000FFFF

_NAMES = {
    FAULT_LINK_DROP: "link_drop",
    FAULT_LINK_DUP: "link_dup",
    FAULT_PARTITION: "partition",
    FAULT_OUTAGE: "outage",
    FAULT_CORRUPT: "corrupt",
    FAULT_STORM: "storm",
    FAULT_SLOWLINK: "slowlink",
    FAULT_ECLIPSE: "eclipse",
    FAULT_CENSOR: "censor",
    FAULT_WAVE: "wave",
    FLAG_NONFINITE: "VIOLATION:nonfinite_counter",
    FLAG_NEG_COUNTER: "VIOLATION:negative_counter",
    FLAG_MESH_DEAD_EDGE: "VIOLATION:mesh_dead_edge",
    FLAG_GRAFT_IN_BACKOFF: "VIOLATION:graft_in_backoff",
    FLAG_SLOT_GARBAGE: "VIOLATION:slot_garbage",
    FLAG_DELIVER_FUTURE: "VIOLATION:deliver_future",
    FLAG_HALO_OVERFLOW: "VIOLATION:halo_overflow",
}


def decode_flags(flags: int, flags_version: int | None = None) -> list[str]:
    """Human-readable names of the set bits (bench lines, trace exports).

    ``flags_version`` is the layout version the word was RECORDED under
    (journal header / crash-dump ``flags_version`` field). Any version
    other than the current :data:`FLAGS_VERSION` is refused by name —
    a version-1 word's violation bits 8-9 would otherwise silently
    misread as FAULT_CENSOR/FAULT_WAVE. ``None`` (a pre-versioning
    artifact) decodes under the current layout, as before."""
    if flags_version is not None and int(flags_version) != FLAGS_VERSION:
        raise ValueError(
            f"fault_flags word was recorded under flags_version="
            f"{int(flags_version)} but this build decodes "
            f"flags_version={FLAGS_VERSION} — the bit layouts differ "
            "(version 1 kept violations at bits 8+, where this layout "
            "puts FAULT_CENSOR/FAULT_WAVE); decode it with the build "
            "that wrote it instead of misreading the bits")
    out = [name for bit, name in sorted(_NAMES.items()) if flags & bit]
    unknown = flags & ~sum(_NAMES)
    if unknown:
        out.append(f"unknown:0x{unknown:x}")
    return out


def _bit(cond, bit) -> jnp.ndarray:
    return jnp.where(cond, U32(bit), U32(0))


def violation_flags(state: SimState, cfg: SimConfig,
                    n_global: int | None = None) -> jnp.ndarray:
    """uint32 scalar of violation bits for the END-OF-TICK state (called by
    engine.step after churn closes the tick, before the tick increments).

    Cost shape: one fused elementwise+reduce pass per array; the big
    [N,T,K] f32 counters dominate (~4 reads of what the tick's attribution
    pass already wrote). NaN is caught by comparison semantics: NaN >= 0
    is False, so the ``>= 0`` check covers NaN and the ``< inf`` check
    covers +Inf — no separate isnan pass.

    ``n_global`` overrides the peer-id range bound for the msg_publisher
    check: a degree-bucket VIEW (sim/bucketed.py) carries a row-sliced
    state whose local row count is NOT the id space — publisher ids are
    global. Every check here is an any() reduction, so per-bucket words
    OR together into exactly the dense word."""
    n, t, k = state.mesh.shape
    if n_global is not None:
        n = n_global
    tick = state.tick
    f = U32(0)

    # NaN/Inf + negativity over the f32 counter planes in one read each
    nonneg = [state.first_message_deliveries, state.mesh_message_deliveries,
              state.mesh_failure_penalty, state.invalid_message_deliveries,
              state.behaviour_penalty, state.gater_validate,
              state.gater_throttle, state.gater_deliver,
              state.gater_duplicate, state.gater_ignore, state.gater_reject]
    bad_neg = jnp.zeros((), bool)
    bad_fin = jnp.zeros((), bool)
    for a in nonneg:
        # both reductions fuse over ONE read of the array; NaN compares
        # False everywhere, so it lands (only) in the nonfinite bit
        bad_neg = bad_neg | jnp.any(a < 0)
        bad_fin = bad_fin | ~jnp.all(jnp.abs(a) < jnp.inf)
    bad_neg = bad_neg | (state.delivered_total < 0) | (state.halo_overflow < 0)
    # app_score may be legitimately negative; only finiteness is invariant
    bad_fin = bad_fin | ~jnp.all(jnp.abs(state.app_score) < jnp.inf) \
        | ~(state.delivered_total < jnp.inf)
    f = f | _bit(bad_fin, FLAG_NONFINITE) | _bit(bad_neg, FLAG_NEG_COUNTER)

    # mesh slots must point at live, known edges (churn/faults clear mesh
    # on RemovePeer — a survivor here means an exchange leaked an edge)
    live = (state.connected & (state.neighbors >= 0))[:, None, :]
    f = f | _bit(jnp.any(state.mesh & ~live), FLAG_MESH_DEAD_EDGE)

    # an edge grafted THIS tick while its backoff was still running: the
    # heartbeat's accept vetting and churn's promote both gate on backoff
    # expiry (gossipsub.go:741-837, 1047-1102), so this firing means a
    # graft path skipped the gate
    f = f | _bit(jnp.any(state.mesh & (state.graft_tick == tick)
                         & (state.backoff > tick)), FLAG_GRAFT_IN_BACKOFF)

    # slot/topic index ranges (bit-plane decodes of packed words land here
    # when tail bits carry garbage: _bits_to_slot/_slot_bitplanes emit
    # out-of-range slot ids if a word's pad bits were ever set)
    bad_rng = jnp.any((state.iwant_pending < -1) | (state.iwant_pending >= k)) \
        | jnp.any((state.deliver_from < -1) | (state.deliver_from >= k)) \
        | jnp.any((state.msg_topic < -1) | (state.msg_topic >= t)) \
        | jnp.any((state.msg_publisher < -1) | (state.msg_publisher >= n))
    f = f | _bit(bad_rng, FLAG_SLOT_GARBAGE)

    # delivery bookkeeping: no future/negative stamps, delivered => seen
    # (the seen-set is stored packed — compare words, 8x fewer bytes)
    from ..ops.bits import pack_words
    dlv = state.deliver_tick < NEVER
    bad_dlv = jnp.any(dlv & (state.deliver_tick > tick)) \
        | jnp.any(dlv & (state.deliver_tick < 0)) \
        | jnp.any(pack_words(dlv) & ~state.have.T)
    f = f | _bit(bad_dlv, FLAG_DELIVER_FUTURE)

    # the halo-route overflow counter folds into the flag word: any routed
    # trajectory with a bucket overflow is poisoned (parallel/halo.py)
    f = f | _bit(state.halo_overflow > 0, FLAG_HALO_OVERFLOW)
    return f


def record_flags(state: SimState, cfg: SimConfig,
                 injected=None, n_global: int | None = None) -> SimState:
    """OR this tick's (injected | violation) bits into the state, and in
    ``"raise"`` mode escalate violations through checkify (callers must be
    checkify-transformed — sim/engine.run_checked)."""
    if cfg.invariant_mode not in ("record", "raise"):
        raise ValueError(
            f"invariant_mode={cfg.invariant_mode!r}: expected 'off', "
            "'record', or 'raise'")
    flags = violation_flags(state, cfg, n_global=n_global)
    if injected is not None:
        flags = flags | injected
    if cfg.invariant_mode == "raise":
        from jax.experimental import checkify
        viol = flags & U32(VIOLATION_MASK)
        checkify.check(viol == 0,
                       "invariant violation: fault_flags={flags}",
                       flags=viol)
    return state._replace(fault_flags=state.fault_flags | flags)
