"""Export a batched-sim run as a pb/trace event stream.

The inverse of trace/replay.py: where replay injects a recorded event
stream into ``SimState``, this module diffs consecutive states of a
``cfg.record_provenance`` run into tracer-bus event dicts (trace/bus.py
shape — the same dicts ``pb.codec.encode_trace_event`` serializes and
``tensorize_trace`` consumes). Together they close the interop loop the
trace schema exists for (SURVEY.md §5.1: the pb/trace contract): a sim run
can be serialized, analyzed by trace tooling, or replayed into a fresh
state.

Event coverage: JOIN/LEAVE, ADD_PEER/REMOVE_PEER (connection churn),
GRAFT/PRUNE, PUBLISH_MESSAGE, DELIVER_MESSAGE (with first-delivery
provenance from ``deliver_from``). Duplicate and reject streams are NOT
exported — the batched engine aggregates them into counters without
per-event provenance — so a replay reproduces mesh/subscription/delivery
state and the P1/P2 counters exactly, while P3/P4 duplicate- and
invalid-driven counters replay as zero.

Timestamps: events of the step that advanced ``tick`` T -> T+1 are stamped
T + 0.5, so tensorize_trace's decay boundaries (at integer seconds, 1s ==
1 tick) interleave exactly like engine.step's decay_counters call (decay
precedes the tick's deliveries; the tick-0 decay acts on all-zero counters
and is a no-op on both sides).
"""

from __future__ import annotations

import jax
import numpy as np

from .config import SimConfig, TopicParams
from .state import SimState


def default_peer_name(i: int) -> str:
    return f"p{i}"


def default_topic_name(t: int) -> str:
    return f"t{t}"


def export_events(prev: SimState, cur: SimState,
                  peer_name=default_peer_name,
                  topic_name=default_topic_name) -> list[dict]:
    """Tracer-bus event dicts for one engine.step (prev -> cur)."""
    prev = jax.device_get(prev)
    cur = jax.device_get(cur)
    tick = int(prev.tick)               # the step that ran
    ts = tick + 0.5
    out: list[dict] = []

    def ev(typ, pid, key, payload):
        out.append({"type": typ, "peerID": peer_name(pid),
                    "timestamp": ts, key: payload})

    # --- subscriptions (churn_subscriptions runs first in the step) ---
    joined = np.argwhere(cur.subscribed & ~prev.subscribed)
    left = np.argwhere(prev.subscribed & ~cur.subscribed)
    for n, t in joined:
        ev("JOIN", n, "join", {"topic": topic_name(t)})
    for n, t in left:
        ev("LEAVE", n, "leave", {"topic": topic_name(t)})

    # --- connection churn (both directions exist in state; each side
    # reports its own view, matching the notifiee fan-out) ---
    nbr = np.asarray(cur.neighbors)
    for n, k in np.argwhere(cur.connected & ~prev.connected):
        ev("ADD_PEER", n, "addPeer", {"peerID": peer_name(nbr[n, k])})
    for n, k in np.argwhere(prev.connected & ~cur.connected):
        ev("REMOVE_PEER", n, "removePeer", {"peerID": peer_name(nbr[n, k])})

    # --- mesh maintenance (heartbeat GRAFT/PRUNE exchange) ---
    for n, t, k in np.argwhere(cur.mesh & ~prev.mesh):
        ev("GRAFT", n, "graft", {"peerID": peer_name(nbr[n, k]),
                                 "topic": topic_name(t)})
    for n, t, k in np.argwhere(prev.mesh & ~cur.mesh):
        ev("PRUNE", n, "prune", {"peerID": peer_name(nbr[n, k]),
                                 "topic": topic_name(t)})

    # --- data plane: publishes then deliveries ---
    pub_slots = np.flatnonzero(np.asarray(cur.msg_publish_tick) == tick)
    mid_of = {}
    for s in pub_slots:
        mid_of[s] = f"m{tick}_{s}"
        ev("PUBLISH_MESSAGE", int(cur.msg_publisher[s]), "publishMessage",
           {"messageID": mid_of[s], "topic": topic_name(int(cur.msg_topic[s]))})

    def mid(s):
        # a slot delivered this tick was published at msg_publish_tick[s]
        return f"m{int(cur.msg_publish_tick[s])}_{s}"

    dlv = np.argwhere((np.asarray(cur.deliver_tick) == tick)
                      & (np.asarray(cur.msg_topic)[None, :] >= 0))
    dfrom = np.asarray(cur.deliver_from)
    publisher = np.asarray(cur.msg_publisher)
    for n, s in dlv:
        topic = topic_name(int(cur.msg_topic[s]))
        if publisher[s] == n and int(cur.msg_publish_tick[s]) == tick:
            rf = peer_name(n)           # local publish: received_from == self
        else:
            slot = dfrom[n, s]
            rf = peer_name(nbr[n, slot]) if slot >= 0 else peer_name(n)
        ev("DELIVER_MESSAGE", n, "deliverMessage",
           {"messageID": mid(s), "topic": topic, "receivedFrom": rf})
    return out


def run_traced(state: SimState, cfg: SimConfig, tp: TopicParams, key,
               n_ticks: int, health_out: list | None = None,
               keys=None):
    """Host-stepped run collecting the exported event stream.

    Returns (final_state, events). Requires cfg.record_provenance. Intended
    for differential testing and trace tooling at diagnostic scale — the
    per-tick host sync makes it unfit for benchmarking.

    ``health_out``: optional list that receives one row dict per tick:
    the full telemetry aggregates (sim/telemetry.py ``health_record``
    columns — per-topic delivery, mesh degree, backoff/graylist census,
    score stats, counters) plus the legacy ``{"tick", "fault_flags",
    "flags"}`` keys (sim/invariants.py bit layout, decoded names) — so an
    exported trace always travels with its health word and a poisoned or
    fault-injected run can never be analyzed as a clean one. The row is
    emitted for EVERY tick regardless of ``invariant_mode``:
    delivery/mesh metrics don't need the flag word, so under ``"off"``
    the record still streams with ``fault_flags``/``flags`` set to None
    (nothing tracked, as opposed to 0 = tracked-and-clean). Kept OUT of
    the event stream itself: the pb/trace wire schema (pb/codec.py) has
    no health message, and replay consumers must keep round-tripping
    byte-exact.

    ``keys``: optional explicit per-tick key array (``key``/``n_ticks``
    are then ignored). Passing ``jax.random.split(key, n_ticks)`` puts the
    traced run on the SAME trajectory as ``engine.run(state, cfg, tp, key,
    n_ticks)`` — the pre-split discipline sim/supervisor.py uses so traced
    chunks stay bit-identical to the single scan. The default (no
    ``keys``) keeps the historical chain-split stream.
    """
    assert cfg.record_provenance, "run_traced needs cfg.record_provenance"
    from .engine import step_jit
    from .invariants import decode_flags
    from .telemetry import health_record_jit, record_to_row

    events: list[dict] = []
    for i in range(n_ticks if keys is None else len(keys)):
        if keys is None:
            key, k = jax.random.split(key)
        else:
            k = keys[i]
        nxt = step_jit(state, cfg, tp, k)
        events.extend(export_events(state, nxt))
        if health_out is not None:
            # the record streams ALWAYS: delivery/mesh aggregates don't
            # need the sentinel; with invariants off the flag keys are
            # None (not tracked) instead of a misleading clean 0
            row = record_to_row(health_record_jit(nxt, cfg, tp))
            if cfg.invariant_mode != "off":
                flags = int(np.asarray(nxt.fault_flags))
                row["fault_flags"] = flags
                row["flags"] = decode_flags(flags)
            else:
                row["fault_flags"] = None
                row["flags"] = None
            health_out.append(row)
        state = nxt
    return state, events
