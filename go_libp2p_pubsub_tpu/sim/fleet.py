"""Fleet plane: B independent simulations as one vmap-batched scan.

"Millions of users" for a simulator means thousands of concurrent
experiments, not one big run (ROADMAP item 3). The engine's step is a pure
scanned-JAX function of ``(state, cfg, tp, key)``, so B members that share
one jit-static ``SimConfig`` — varying seeds, score weights
(``TopicParams`` rows are traced arrays), and initial states — run as ONE
``vmap``-batched scan: one dispatch, one compiled program, B lanes of MXU
work, instead of B sequential dispatches that each leave a tiny-N config
nowhere near filling the chip (the 1k config runs ~52–85 hb/s on CPU;
bench.py's ``fleet_256x1k`` line measures the aggregate multiplier).

Semantics, in order of importance:

- **bit-exact per member**: ``vmap`` is semantics-preserving, and each
  member's key discipline is exactly ``engine.run``'s (the member key is
  pre-split into per-tick keys once; every window scans a contiguous
  slice), so member i's trajectory equals ``engine.run(state_i, cfg_i,
  tp_i, key_i, n_ticks_i)`` bit for bit (tests/test_fleet.py, the core
  claim — plain, under faults, and across kill/resume).
- **config grouping**: members are grouped by their (normalized) jit-static
  ``SimConfig``; each group is one batched scan. Members whose configs
  differ — a FaultPlan on one member, a P5–P7 weight variant (static
  floats) — land in separate groups and still run, so a sweep mixes
  batched and singleton members freely. Grouping never reorders results:
  they return in input order.
- **per-member fault isolation**: ``SimState.fault_flags`` is per-lane, so
  one member's injected faults or invariant violations never taint a
  sibling's flags. ``invariant_mode="raise"`` members execute in
  ``"record"`` (identical state math — ``record_flags`` writes the same
  flags either way and the checkify check writes nothing) and are
  RETIRED at the first chunk boundary where a violation bit shows: the
  member's state freezes (``FleetResult.tripped``), its siblings keep
  running — one poisoned lane must not kill or mask B-1 healthy ones.
- **early-exit compaction**: members finish at their own ``n_ticks`` (or
  retire on a trip); finished lanes are compacted OUT of the batch at
  window boundaries, so a long-tail member doesn't hold B-1 idle lanes of
  compute. Windows end exactly at member-finish ticks (the chunk length is
  ``min(chunk, min remaining among active)``), so compaction never splits
  a member's key stream mid-window.
- **supervision**: :func:`supervised_fleet_run` composes with the
  supervised execution plane (sim/supervisor.py): per-window wall-clock
  watchdog, retry/backoff down the same degraded-mode ladder, crash-atomic
  fleet checkpoints at chunk boundaries whose fingerprint sidecar BINDS
  the fleet axis (checkpoint.config_fingerprint(fleet=B) — a B=4 journal
  can never resume into B=8), resume that verifies every member's tick
  against the schedule, and fleet crash dumps with per-member flags.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import checkpoint
from ..parallel import compile_plan
from .config import SimConfig, TopicParams
from .state import SimState
from .supervisor import (_CONFIRM_GRACE_S, SupervisorConfig, SupervisorCrash,
                         SupervisorReport, _degrade, _key_data,
                         _prune_checkpoints, _with_deadline, _Writer,
                         list_checkpoints)


@dataclasses.dataclass(frozen=True)
class FleetMember:
    """One lane of a fleet: a full (cfg, tp, state, key, n_ticks) run
    spec, exactly what ``engine.run`` takes. ``name`` labels the member in
    reports, sweep rows, and crash dumps."""

    cfg: SimConfig
    tp: TopicParams
    state: SimState
    key: jax.Array
    n_ticks: int
    name: str = ""


@dataclasses.dataclass
class FleetResult:
    """Per-member outcome. ``state`` is the member's final SimState
    (bit-identical to its sequential run); ``tripped`` marks a member
    whose ``invariant_mode="raise"`` sentinel fired — its state is frozen
    at the end of the window where the trip was detected.
    ``health_rows`` (``collect_health=True`` runs only) is the member's
    full per-tick telemetry row stream (sim/telemetry.py dict rows) — the
    input the adversary behavior contracts evaluate per member
    (sim/adversary.py evaluate_contracts; scripts/sweep_scores.py
    contract columns)."""

    name: str
    state: SimState
    ticks_run: int
    fault_flags: int
    flag_names: list
    tripped: bool
    health_rows: list | None = None


# ---------------------------------------------------------------------------
# the batched core


def _fleet_run_keys_impl(states: SimState, cfg: SimConfig, tps: TopicParams,
                         keys: jax.Array, telemetry: bool = False):
    """Advance B stacked members one tick per row of ``keys`` ([C, B]
    per-tick-major, so the scan consumes one tick across all lanes per
    iteration). The vmapped step is the UNCHANGED ``engine.step`` — the
    fleet adds a batch axis, not semantics.

    ``telemetry=True`` (static) stacks the per-member device-side health
    reduction alongside: the vmapped ``telemetry.health_record`` over the
    post-step lanes, scanned into ``[C, B]``-leaved records, returned as
    ``(states, HealthRecord)`` — the fleet flavor of ``engine.run_keys``'
    telemetry lane (sim/telemetry.py)."""
    from .engine import step
    from .telemetry import health_record

    vstep = jax.vmap(lambda s, t, k: step(s, cfg, t, k))
    vhealth = jax.vmap(lambda s, t: health_record(s, cfg, t))

    def body(carry, keys_t):
        nxt = vstep(carry, tps, keys_t)
        return nxt, vhealth(nxt, tps) if telemetry else None

    out, health = jax.lax.scan(body, states, keys)
    return (out, health) if telemetry else out


fleet_run_keys = jax.jit(_fleet_run_keys_impl,
                         static_argnames=("cfg", "telemetry"))
# the bench path: donating the batched state halves peak fleet memory
fleet_run_keys_donated = jax.jit(_fleet_run_keys_impl,
                                 static_argnames=("cfg", "telemetry"),
                                 donate_argnums=(0,))


def stack_states(items: list) -> SimState | TopicParams:
    """Stack member pytrees (SimState or TopicParams) along a new leading
    fleet axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), items[0], *items[1:])


def fleet_devices(b: int, devices: list | None = None) -> int:
    """How many local devices a B-lane fleet can shard across: the largest
    device count that divides B (1 when it can't split evenly)."""
    d = len(devices) if devices is not None else jax.local_device_count()
    return max(k for k in range(1, d + 1) if b % k == 0)


def shard_fleet(states: SimState, tps: TopicParams, keys=None,
                devices: list | None = None):
    """Place a stacked fleet with the FLEET axis sharded across local
    devices. Members are independent, so the batched scan is
    embarrassingly SPMD over this axis — GSPMD partitions every op with
    ZERO collectives, and B lanes on D devices run D-wide in parallel.
    This is the fleet's scaling story beyond one chip: vmap fills a
    single accelerator's lanes, the fleet-axis sharding fills the other
    D-1 devices (and on CPU, a forced multi-device host mesh turns lanes
    into cores — bench.py's fleet line does this automatically).

    Returns ``(states, tps)`` or ``(states, tps, keys)`` when per-tick
    keys are passed — one [C, B, ...] window (fleet axis SECOND) or a
    list of them. A B not divisible by the device count shards across
    the largest dividing subset (:func:`fleet_devices`); D=1 is a no-op
    placement."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = list(devices) if devices is not None else jax.devices()
    b = int(np.shape(states.tick)[0])
    d = fleet_devices(b, devs)
    mesh = Mesh(np.array(devs[:d]), ("fleet",))

    def put(tree, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)

    states = put(states, PartitionSpec("fleet"))
    tps = put(tps, PartitionSpec("fleet"))
    if keys is None:
        return states, tps
    kspec = PartitionSpec(None, "fleet")
    if isinstance(keys, (list, tuple)):
        return states, tps, [put(k, kspec) for k in keys]
    return states, tps, put(keys, kspec)


def member_state(batched, i: int):
    """Member ``i``'s unbatched pytree out of a fleet-stacked one."""
    return jax.tree.map(lambda x: x[i], batched)


def _take_rows(tree, idx):
    return jax.tree.map(lambda x: x[idx], tree)


def _put_rows(full, idx, rows):
    return jax.tree.map(lambda f, r: f.at[idx].set(r), full, rows)


def _exec_cfg(cfg: SimConfig) -> SimConfig:
    """The config a member EXECUTES under. ``"raise"`` checkifies the
    whole batch — one member's trip would throw away B-1 healthy lanes —
    so raise-mode members run ``"record"`` (bit-identical state: the
    flags land in ``fault_flags`` either way, the check writes nothing)
    and the driver retires them at the boundary where a violation bit
    appears."""
    if cfg.invariant_mode == "raise":
        return dataclasses.replace(cfg, invariant_mode="record")
    return cfg


# The fleet window runs split into a DISPATCH phase (hook + enqueue the
# batched scan — returns futures) and a CONFIRM phase (block on the
# window's tick, deadline re-anchored to time already spent in flight),
# the fleet flavor of the supervisor's latency-hiding pipeline: while
# window k runs on device, the driver builds and dispatches window k+1
# and the writer thread drains window k-1's journal/checkpoint I/O.
# First-use bookkeeping (which shapes compiled, and hence which deadline
# applies) lives in parallel/compile_plan.fleet_chunk — plain-jit on
# purpose, see the const-hoisting rationale there.


def _dispatch_window(w, exec_cfg, sup, hook, telemetry: bool = False):
    """Enqueue one window attempt; returns a pending dict whose ``out``/
    ``health`` leaves are device futures. Only the hook + dispatch run
    under the deadline here — the device-time budget is enforced by
    :func:`_confirm_window`."""
    run_fn, first_use = compile_plan.fleet_chunk(
        exec_cfg, w["keys"].shape, w["keys"].dtype, telemetry=telemetry,
        mark=False)

    def worker():
        if hook is not None:            # test/smoke fault-injection point
            hook(w["info"])
        res = run_fn(w["sub"], exec_cfg, w["sub_tps"], w["keys"],
                     telemetry=telemetry)
        return res if telemetry else (res, None)

    # a first-use window compiles AND runs: bound it by the compile
    # deadline (unbounded by default — compile time is not execution
    # time, sim/supervisor.py rationale), steady-state windows by the
    # run watchdog
    deadline = sup.compile_deadline_s if first_use else sup.deadline_s
    out, health = _with_deadline(worker, deadline,
                                 "fleet compile+window" if first_use
                                 else "fleet window", w["info"])
    return {"w": w, "out": out, "health": health, "cfg": exec_cfg,
            "telemetry": telemetry, "first_use": first_use,
            "at": time.monotonic()}


def _confirm_window(pend, sup) -> None:
    """Block until the pending window's device result lands, under the
    remainder of its deadline (total budget minus time already in flight
    since dispatch, floored at the grace period so a window that ran
    while the driver was busy elsewhere is not spuriously killed)."""
    budget = sup.compile_deadline_s if pend["first_use"] else sup.deadline_s
    deadline = None
    if budget is not None:
        deadline = max(_CONFIRM_GRACE_S,
                       budget - (time.monotonic() - pend["at"]))
    _with_deadline(lambda: np.asarray(pend["out"].tick), deadline,
                   "fleet compile+window" if pend["first_use"]
                   else "fleet window", pend["w"]["info"])
    # mark the shape compiled only now: a window that died mid-compile
    # keeps its compile-deadline budget on retry
    compile_plan.fleet_chunk(pend["cfg"], pend["w"]["keys"].shape,
                             pend["w"]["keys"].dtype,
                             telemetry=pend["telemetry"])


# ---------------------------------------------------------------------------
# supervision plumbing (fleet flavor of the sim/supervisor.py pieces)


def _ckpt_path(ckpt_dir: str, done: int) -> str:
    # "tick" in the checkpoint name is the GROUP's window progress, not a
    # member's absolute tick (members may start at different ticks and
    # finish at different n_ticks)
    return os.path.join(ckpt_dir, f"ckpt_t{done:09d}")


def _expected_ticks(starts, n_ticks, done: int) -> np.ndarray:
    return starts + np.minimum(done, np.asarray(n_ticks, np.int64))


def _try_resume_fleet(sup, ckpt_dir, group_cfg, full, starts, n_ticks,
                      escalate, report, gi):
    """Newest fleet checkpoint that restores cleanly AND whose per-member
    ticks match the deterministic window schedule at its recorded
    progress; tripped members (violation bits set on a raise-mode lane)
    are exempt from the progress check — they froze early by design."""
    from .invariants import VIOLATION_MASK

    for path, done in reversed(list_checkpoints(ckpt_dir)):
        try:
            st = checkpoint.restore(path, full, cfg=group_cfg)
        except ValueError as e:         # corrupt, mismatched, wrong fleet
            report.log("resume_skip", group=gi, path=path,
                       error=str(e)[:200])
            continue
        ticks = np.asarray(st.tick)
        flags = np.asarray(st.fault_flags)
        tripped = [bool(esc and (int(f) & VIOLATION_MASK))
                   for esc, f in zip(escalate, flags)]
        want = _expected_ticks(starts, n_ticks, done)
        ok = all(t == w or tr
                 for t, w, tr in zip(ticks, want, tripped))
        if not ok:
            report.log("resume_skip", group=gi, path=path,
                       error=f"member ticks {ticks.tolist()} do not match "
                             f"schedule at done={done}")
            continue
        report.resumed_from = path
        report.resumed_tick = done
        report.log("resume", group=gi, path=path, done=done)
        return st, done, tripped
    return full, 0, [False] * len(n_ticks)


def _write_fleet_crash_dump(sup, group_cfg, full, keys_win, gi, active,
                            names, idxs, done, this_win, err,
                            report) -> str:
    from .invariants import FLAGS_VERSION, decode_flags

    base = sup.crash_dir or os.environ.get("GRAFT_CRASH_DIR") \
        or os.path.join(os.getcwd(), "graft_crash")
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    dump = os.path.join(base, f"crash_fleet_{stamp}_p{os.getpid()}")
    os.makedirs(dump, exist_ok=True)
    checkpoint.save(os.path.join(dump, "last_good"), full, cfg=group_cfg)
    flags = [int(f) for f in np.asarray(full.fault_flags)]
    meta = {
        "error": str(err)[:2000],
        "error_type": type(err).__name__,
        "fleet_group": gi,
        "fleet_size": len(names),
        "member_names": names,
        # the members' INPUT indices, group-position-ordered: a mixed-
        # config fleet splits into groups, so group position != input
        # index — replay_crash maps --member (input index) through this
        "member_ids": [int(i) for i in idxs],
        "active_members": active,
        "window_start": done,
        "window_end": done + this_win,
        "config_fingerprint": checkpoint.config_fingerprint(
            group_cfg, fleet=len(names)),
        "fault_flags": flags,
        # bit-layout version of the words above: replay refuses by name
        # to decode another version's bits (sim/invariants.py)
        "flags_version": FLAGS_VERSION,
        "fault_flag_names": [decode_flags(f) for f in flags],
        # [C, B_active] per-tick keys of the failing window, replay-ready
        "window_key_data": _key_data(keys_win).tolist(),
        "degrade_level": report.degrade_level,
        "retries": report.retries,
    }
    tmp = os.path.join(dump, f"crash.json.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dump, "crash.json"))
    report.log("crash_dump", group=gi, path=dump)
    return dump


# ---------------------------------------------------------------------------
# the driver


def _drive_group(gi, idxs, members, sup, report, dumps, hook,
                 journal=None, collect_health=False, writer=None) -> dict:
    """Run one config group to completion; {input_index: FleetResult}."""
    from .invariants import VIOLATION_MASK, decode_flags

    group_cfg = _exec_cfg(members[idxs[0]].cfg)
    escalate = [members[i].cfg.invariant_mode == "raise" for i in idxs]
    names = [members[i].name or f"member{i}" for i in idxs]
    n_ticks = [int(members[i].n_ticks) for i in idxs]
    b = len(idxs)
    full = stack_states([members[i].state for i in idxs])
    tps = stack_states([members[i].tp for i in idxs])
    # each member's per-tick keys, pre-split ONCE with engine.run's exact
    # discipline — windows slice this array, never re-split
    all_keys = [jax.random.split(members[i].key, members[i].n_ticks)
                if members[i].n_ticks > 0 else None for i in idxs]
    starts = np.asarray(full.tick, np.int64).copy()

    done = 0
    tripped = [False] * b
    ckpt_dir = None
    if sup.checkpoint_dir:
        ckpt_dir = os.path.join(sup.checkpoint_dir, f"fleet_g{gi:02d}")
        full, done, tripped = _try_resume_fleet(
            sup, ckpt_dir, group_cfg, full, starts, n_ticks, escalate,
            report, gi)

    if journal is not None:
        # per-group header: a mixed-config fleet writes one journal with
        # groups interleaved; the member ids bind rows back to input order
        journal.header(group_cfg, plane="fleet", group=gi,
                       member_ids=list(map(int, idxs)), member_names=names,
                       n_ticks=n_ticks, resumed_done=done,
                       **(sup.health_meta or {}))
    exec_cfg = group_cfg
    chunk_ticks = max(1, int(sup.chunk_ticks))
    every = sup.checkpoint_every_ticks or chunk_ticks
    next_ckpt = done + every
    failures = 0
    prev_active = b
    if writer is None:                  # direct callers outside _drive
        writer = _Writer(maxsize=sup.writer_queue,
                         flush=journal.sync if journal is not None else None,
                         threaded=False)
    # Speculating window k+1 needs its active set/length to be a pure
    # function of (done, tripped) BEFORE window k confirms — but escalate
    # lanes retire on confirmed violation flags, so a group holding any
    # raise-mode member runs the degenerate (sync) pipeline instead.
    pipelined = bool(sup.async_chunks) and not any(escalate)
    telemetry = journal is not None or collect_health
    # collect_health: per-member telemetry row accumulation (input-index
    # keyed — compaction changes lane positions, never ids). A RESUMED
    # run's pre-restore ticks are not re-collected; contract evaluation
    # over a resumed fleet should read the journal instead.
    health_rows: dict = {int(i): [] for i in idxs} if collect_health else {}
    def build_window(state_now, done_now):
        """The next window spec from a state pytree — which may still be
        an in-flight device future: compaction slicing (`_take_rows`) and
        key stacking compose asynchronously, so speculation builds window
        k+1's inputs from window k's unconfirmed output for free."""
        act = [j for j in range(b)
               if not tripped[j] and done_now < n_ticks[j]]
        if not act:
            return None
        tw = min(chunk_ticks, min(n_ticks[j] - done_now for j in act))
        whole = len(act) == b
        idx = None if whole else jnp.asarray(act, jnp.int32)
        return {
            "active": act, "this_win": tw, "whole": whole, "idx": idx,
            "done": done_now,
            "sub": state_now if whole else _take_rows(state_now, idx),
            "sub_tps": tps if whole else _take_rows(tps, idx),
            "keys": jnp.stack([all_keys[j][done_now:done_now + tw]
                               for j in act], axis=1),
            "info": {"group": gi, "window_start": done_now,
                     "window_ticks": tw, "b_active": len(act),
                     "attempt": failures,
                     "degrade_level": report.degrade_level},
        }

    def note_compact(w):
        nonlocal prev_active
        if len(w["active"]) < prev_active:
            report.log("compact", group=gi, active=len(w["active"]),
                       retired=[names[j] for j in range(b)
                                if j not in w["active"]])
        prev_active = len(w["active"])

    def handle_failure(e, w):
        nonlocal exec_cfg, chunk_ticks, failures
        if not dumps:
            raise e     # plain fleet_run: no retry net, no dumps
        failures += 1
        if failures > sup.max_retries:
            # durability first: land every queued journal row/checkpoint
            # before dumping, so the dump describes a settled run
            writer.drain(raise_errors=False)
            dump = _write_fleet_crash_dump(
                sup, group_cfg, full, w["keys"], gi, w["active"], names,
                idxs, w["done"], w["this_win"], e, report)
            report.crash_dump = dump
            if journal is not None:
                journal.note("crash", group=gi, dump=dump,
                             error=str(e)[:200])
                journal.sync()
            raise SupervisorCrash(
                f"fleet group {gi} gave up at window start {w['done']} "
                f"({failures} consecutive failure(s)); crash dump: "
                f"{dump}", dump_dir=dump, report=report) from e
        report.retries += 1
        report.log("chunk_failed", error=str(e)[:200], **w["info"])
        exec_cfg, chunk_ticks = _degrade(exec_cfg, chunk_ticks, sup,
                                         report)
        delay = min(sup.backoff_cap_s, sup.backoff_base_s
                    * sup.backoff_factor ** (failures - 1))
        report.log("backoff", delay_s=round(delay, 3))
        sup.sleep(delay)

    def process(p):
        """Fold a confirmed window in: merge state, advance progress,
        hand journal/checkpoint I/O to the writer thread."""
        nonlocal full, done, failures, next_ckpt
        w = p["w"]
        act, tw = w["active"], w["this_win"]
        done_wall = time.time()     # dispatch-complete stamp (dashboard)
        failures = 0
        full = p["out"] if w["whole"] \
            else _put_rows(full, w["idx"], p["out"])
        done = w["done"] + tw
        report.chunks_run += 1
        report.ticks_run += tw * len(act)       # member-ticks
        report.log("chunk_ok", **w["info"])
        if journal is not None and p["health"] is not None:
            # [C, B_active] records, fetched OFF the critical path on the
            # writer thread, rows bound to the members' INPUT indices
            # (compaction changes lane positions, never ids); a failed
            # attempt's records never reach here
            writer.submit(
                lambda h=p["health"], m=[int(idxs[j]) for j in act],
                t0=w["done"], dw=done_wall: journal.append_records(
                    h, member_ids=m, group=gi, window_start=t0,
                    ticks=tw, done_wall=dw))
        if collect_health and p["health"] is not None:
            from .telemetry import records_to_rows, rows_to_dicts
            mat, cols = records_to_rows(
                p["health"], member_ids=[int(idxs[j]) for j in act])
            for r in rows_to_dicts(mat, cols):
                health_rows[r["member"]].append(r)
        # per-member sentinel surfacing: a raise-mode lane whose violation
        # bits lit retires HERE, its siblings keep running
        if any(escalate):
            flags = np.asarray(p["out"].fault_flags)
            for pos, j in enumerate(act):
                if escalate[j] and not tripped[j] \
                        and int(flags[pos]) & VIOLATION_MASK:
                    tripped[j] = True
                    report.log("member_tripped", group=gi, member=names[j],
                               done=done,
                               flags=decode_flags(int(flags[pos])))
        if ckpt_dir and (done >= next_ckpt
                         or not any(not tripped[j] and done < n_ticks[j]
                                    for j in range(b))):
            path = _ckpt_path(ckpt_dir, done)

            def save(full_now=full, path=path):    # fleet-axis bound
                os.makedirs(ckpt_dir, exist_ok=True)
                checkpoint.save(path, full_now, cfg=group_cfg)
                _prune_checkpoints(ckpt_dir, sup.keep_checkpoints)

            writer.submit(save)
            report.checkpoints.append(path)
            report.log("checkpoint", group=gi, done=done, path=path)
            if journal is not None:
                writer.submit(lambda d=done, pth=path: journal.note(
                    "checkpoint", group=gi, done=d, path=pth))
            next_ckpt = done + every

    pend = None
    while True:
        if pend is None:                # start, or refill after failure
            w = build_window(full, done)
            if w is None:
                break
            note_compact(w)
            try:
                pend = _dispatch_window(w, exec_cfg, sup, hook,
                                        telemetry=telemetry)
            except Exception as e:
                handle_failure(e, w)
                continue
        # speculate window k+1 against window k's in-flight output while
        # the device still runs k (fleet never donates, so a failed k
        # retries from the intact `full` and the speculation just drops)
        spec = None
        spec_exc = None
        if pipelined and failures == 0:
            w_p = pend["w"]
            merged = pend["out"] if w_p["whole"] \
                else _put_rows(full, w_p["idx"], pend["out"])
            w2 = build_window(merged, w_p["done"] + w_p["this_win"])
            if w2 is not None:
                try:
                    spec = _dispatch_window(w2, exec_cfg, sup, hook,
                                            telemetry=telemetry)
                except Exception as e:
                    spec_exc = (e, w2)  # settle pend first, then ladder
                except BaseException:
                    # KeyboardInterrupt/SystemExit mid-speculation: land
                    # the in-flight window's checkpoint/journal rows
                    # before surfacing, so resume starts from them
                    try:
                        _confirm_window(pend, sup)
                        process(pend)
                        writer.drain(raise_errors=False)
                    except Exception:
                        pass
                    raise
        try:
            _confirm_window(pend, sup)
        except Exception as e:
            if spec is not None or spec_exc is not None:
                report.log("spec_discarded", group=gi,
                           window_start=pend["w"]["done"]
                           + pend["w"]["this_win"])
            w_failed = pend["w"]
            pend = None
            handle_failure(e, w_failed)
            continue
        process(pend)
        pend = None
        if spec_exc is not None:
            e2, w2 = spec_exc
            note_compact(w2)
            handle_failure(e2, w2)
            continue
        if spec is not None:
            note_compact(spec["w"])
        pend = spec

    flags = np.asarray(full.fault_flags)
    ticks = np.asarray(full.tick, np.int64)
    out: dict = {}
    for j, i in enumerate(idxs):
        fj = int(flags[j])
        out[i] = FleetResult(
            name=names[j], state=member_state(full, j),
            ticks_run=int(ticks[j] - starts[j]), fault_flags=fj,
            flag_names=decode_flags(fj), tripped=tripped[j],
            health_rows=health_rows.get(int(i)) if collect_health else None)
    return out


def _drive(members, sup, dumps, hook, collect_health=False):
    if not members:
        return [], SupervisorReport()
    for m in members:
        if m.n_ticks < 0:
            raise ValueError(f"member {m.name!r}: n_ticks must be >= 0")
    report = SupervisorReport()
    # group by the normalized jit-static config, preserving first-seen
    # order; every group is one batched scan
    groups: dict = {}
    for i, m in enumerate(members):
        groups.setdefault(_exec_cfg(m.cfg), []).append(i)
    report.log("fleet_plan", members=len(members), groups=len(groups),
               sizes=[len(v) for v in groups.values()])
    # streaming-telemetry lane (sim/telemetry.py): one journal for the
    # whole fleet, rows [B]-batched per window and bound to input indices.
    # Under the async pipeline the journal batches fsyncs per writer-queue
    # drain instead of per write (the writer flushes whenever its queue
    # runs dry, and drain() barriers bound the loss window).
    pipelined = bool(sup.async_chunks)
    journal = None
    if sup.health_path and sup.write_files:
        from .telemetry import HealthJournal
        journal = HealthJournal(sup.health_path,
                                sync_every_write=not pipelined)
    # ONE off-critical-path writer for the whole fleet: checkpoint
    # serialization and journal encode+fsync ride it; sync mode degrades
    # to inline execution at submit (sim/supervisor.py._Writer)
    writer = _Writer(maxsize=sup.writer_queue,
                     flush=journal.sync if journal is not None else None,
                     threaded=pipelined)
    results: dict = {}
    try:
        for gi, idxs in enumerate(groups.values()):
            results.update(_drive_group(gi, idxs, members, sup, report,
                                        dumps, hook, journal=journal,
                                        collect_health=collect_health,
                                        writer=writer))
            # group-end barrier: queued I/O lands (and any deferred
            # writer error surfaces) before the next group's header
            writer.drain()
    finally:
        writer.close()
        if journal is not None:
            journal.close()
    return [results[i] for i in range(len(members))], report


def fleet_run(members: list, chunk_ticks: int | None = None,
              collect_health: bool = False) -> list:
    """Run a fleet unsupervised: no watchdog, no retries, no checkpoints —
    failures propagate. ``chunk_ticks`` bounds the window length (windows
    also end at member finishes for compaction); None scans each group's
    longest common stretch in one dispatch. Returns ``[FleetResult]`` in
    input order; bit-exact per member vs sequential ``engine.run``.
    ``collect_health=True`` runs the telemetry lane and attaches each
    member's per-tick row stream (``FleetResult.health_rows``) — the
    fleet entry point for adversary contract evaluation."""
    sup = SupervisorConfig(chunk_ticks=chunk_ticks or (1 << 30),
                           max_retries=0, backoff_base_s=0.0,
                           sleep=lambda s: None)
    results, _ = _drive(members, sup, dumps=False, hook=None,
                        collect_health=collect_health)
    return results


def supervised_fleet_run(members: list, sup: SupervisorConfig | None = None,
                         *, collect_health: bool = False,
                         _chunk_hook=None) -> tuple:
    """Run a fleet under the supervised execution plane (module
    docstring): chunked windows with watchdog + retry/degrade ladder,
    crash-atomic fleet-axis-bound checkpoints in
    ``sup.checkpoint_dir/fleet_gNN/``, resume, and fleet crash dumps.
    Returns ``([FleetResult], SupervisorReport)``. ``collect_health``
    as in :func:`fleet_run` (independent of ``sup.health_path`` — a run
    may stream, collect, both, or neither)."""
    sup = sup or SupervisorConfig.from_env()
    return _drive(members, sup, dumps=True, hook=_chunk_hook,
                  collect_health=collect_health)
