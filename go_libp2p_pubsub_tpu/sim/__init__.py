from .config import SimConfig, TopicParams  # noqa: F401
from .state import SimState, init_state  # noqa: F401
from . import topology  # noqa: F401

_ENGINE_EXPORTS = ("delivery_fraction", "delivery_latency_ticks", "mesh_degrees", "run", "step", "step_jit",
                   "choose_publishers")
_SUPERVISOR_EXPORTS = ("supervised_run", "SupervisorConfig",
                       "SupervisorReport", "SupervisorCrash")
_FLEET_EXPORTS = ("FleetMember", "FleetResult", "fleet_run",
                  "supervised_fleet_run", "fleet_run_keys", "stack_states",
                  "member_state")
_CONFIG_EXPORTS = ("with_score_weights", "SCORE_WEIGHT_KEYS")
_TELEMETRY_EXPORTS = ("HealthRecord", "HealthJournal", "health_record",
                      "read_journal")


def __getattr__(name):
    # engine depends on ops/, which depends back on sim.config — lazy import
    # keeps `import go_libp2p_pubsub_tpu.ops.heartbeat` cycle-free
    if name in _ENGINE_EXPORTS:
        from . import engine
        return getattr(engine, name)
    if name in _SUPERVISOR_EXPORTS:
        from . import supervisor
        return getattr(supervisor, name)
    if name in _FLEET_EXPORTS:
        from . import fleet
        return getattr(fleet, name)
    if name in _CONFIG_EXPORTS:
        from . import config
        return getattr(config, name)
    if name in _TELEMETRY_EXPORTS:
        from . import telemetry
        return getattr(telemetry, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
