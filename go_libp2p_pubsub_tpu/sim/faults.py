"""Declarative fault injection for both halves of the framework.

The reference router's whole reason for existing (gossipsub v1.1, SURVEY.md
§2 scoring P1-P7) is behavior under hostile and DEGRADED networks, yet the
engine could only exercise the failure modes baked into the five BASELINE
scenarios. A ``FaultPlan`` is a jit-static description of what goes wrong
and when, applied every tick by ``sim/engine.step`` (batched half) or
installed on the discrete-event scheduler by :class:`HostFaultInjector`
(functional-runtime half, via the ``Network.link_fault`` hook in
net/network.py) — the SAME plan runs against both halves, so recovery
behavior (partition heal, outage return, mesh self-healing time) can be
parity-checked between them.

Fault classes:

- **link drop** (``link_drop_prob``): each tick, each directed edge loses
  its DATA plane with this probability — eager forwards, flood publishes,
  and IWANT-pull answers on the edge vanish in flight. Control traffic
  (GRAFT/PRUNE/IHAVE) still flows, like the peer gater's RED drops
  (peer_gater.go:320-363 strips data, keeps control): the batched
  exchange's edge symmetry must hold, and real links drop big data frames
  long before tiny control frames. A link-eaten pull answer IS charged as
  a broken promise: the promise tracker fires on non-delivery at expiry
  whatever the cause (gossip_tracer.go:79-115; the host half's tracer
  behaves the same), so P7 scoring stays parity-comparable between
  halves under a drop plan.
- **link duplication** (``link_dup_prob``): each tick, a duplicating mesh
  edge re-offers its recent deliveries (the mcache gossip slice) alongside
  the frontier — seen-cache hits count as mesh duplicates (P3 credit,
  score.go:949-981) and gater duplicates, exactly where a re-transmitted
  RPC would land in the reference.
- **partitions** (``partitions``): on a tick schedule, peers split into
  ``components`` by ``peer_id % components``; cross-component edges go
  DOWN with full RemovePeer semantics (ops/churn.take_edges_down —
  pubsub.go:711-757 dead-peer path, score retention per score.go:611-644)
  and come back at the window's ``end`` tick through the reconnect path
  (retention expiry included), so mesh self-healing and backoff are
  genuinely exercised, not simulated around.
- **regional outages** (``outages``): a deterministic pseudo-random
  ``fraction`` of peers goes completely dark for the window (all their
  edges down, RemovePeer semantics), then returns through the same
  churn/backoff/retention path. Peer choice uses a shared integer hash
  (:func:`outage_peers`) so the batched and host halves pick the SAME
  peers.
- **corruption** (``corrupt_prob``): each honest publish draws this
  probability of being corrupted in flight — honest receivers REJECT it
  and charge P4 invalid-message deliveries (score.go:899-918), feeding the
  scoring pipeline invalid traffic that no sybil actor sent.

Beyond the original fault classes, the plan carries the ADVERSARY /
WORKLOAD families of ISSUE 10 (ROADMAP item 4 — the gossipsub v1.1
hardening evaluation set, Vyzovitis et al.):

- **eclipse** (``eclipses``): for a tick window, every edge between a
  TARGET (an honest peer in the contiguous id region
  ``[0, ceil(fraction*N))``) and an honest NON-target is cut with
  RemovePeer semantics — the targets keep only their sybil
  (``state.malicious``) neighbors, so heartbeat under-subscription grafts
  sybils into the targets' meshes (GRAFT pressure) and the window heals
  through the same redial path as a partition. The region is id-contiguous
  so both halves (and the host injector's ``malicious`` list) pick the
  same targets.
- **censorship** (``censorships``): a hash-chosen ``fraction`` of honest
  peers suppress the ``victim`` peer's messages while the window is
  active: no IHAVE advertisement, no IWANT answer, no forwarding — but
  they still RECEIVE them (score-gamed: the censor behaves perfectly on
  all other traffic). Unanswered pulls for censored messages are charged
  as broken promises (P7) and withheld mesh forwarding starves P3 credit
  — the scoring machinery the contract must show responding. Applied via
  :func:`censor_word_mask` in engine.step; the fused Pallas hop is
  ineligible under a censor plan (ops/hopkernel.py gate) because the
  per-sender frontier mask cannot enter the kernel.
- **flash-crowd storms** (``storms``): while a window is active each
  publisher slot redraws, with probability ``skew``, from the ``hot``
  lowest peer ids and publishes to the window's ``topic`` — a hot-topic
  publish storm with a skewed publisher distribution
  (:func:`storm_publishers`, consumed by engine.choose_publishers).
- **slow links** (``slowlinks``): heterogeneous per-edge delay/drop
  classes layered on the drop/dup link model. A symmetric edge hash
  assigns each class's ``fraction`` of edges; a member edge's DATA plane
  opens only every ``period``-th tick (a phase from the same hash — the
  tick-quantized stand-in for a high-RTT/low-bandwidth link) and drops
  with ``drop`` even when open. Control always flows.
- **diurnal churn waves** (``waves``): a hash-chosen cohort
  (``fraction``) goes dark for the first ``duty`` ticks of every
  ``period``-tick cycle (offset ``phase``) until ``until`` — scheduled
  join/leave waves through the same churn ops (take_edges_down /
  bring_edges_up) as outages, one expanded window per cycle
  (:func:`wave_windows`).

Every random draw is keyed off the step key (batched) or a
``random.Random(plan.seed)`` stream (host), so runs are reproducible; the
plan itself is a frozen dataclass, hashable, and lives on ``SimConfig`` as
a jit-static field — a plan change recompiles, a key change replays.

Which faults fired is recorded per tick into ``SimState.fault_flags``
(sim/invariants.py bit layout), making every degraded run self-identifying
in bench lines and trace exports.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .config import SimConfig, TopicParams
from .state import SimState

U32 = jnp.uint32


# ---------------------------------------------------------------------------
# the plan


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """Split the network into ``components`` (peer_id % components) for
    ticks ``start <= tick < end``; heal (redial the cut edges) at
    ``end``."""

    start: int
    end: int
    components: int = 2


@dataclasses.dataclass(frozen=True)
class OutageWindow:
    """A ``fraction`` of peers goes completely dark for ticks
    ``start <= tick < end``, returning at ``end`` through the reconnect
    path. Peer choice is :func:`outage_peers` (shared across halves)."""

    start: int
    end: int
    fraction: float = 0.1


@dataclasses.dataclass(frozen=True)
class EclipseWindow:
    """Sybil mesh takeover of a target region for ticks
    ``start <= tick < end``: edges between an honest TARGET (peer id <
    ceil(fraction*N)) and an honest non-target go down with RemovePeer
    semantics, leaving the targets only their ``malicious`` neighbors;
    the cut redials at ``end`` through the partition heal path."""

    start: int
    end: int
    fraction: float = 0.1


@dataclasses.dataclass(frozen=True)
class CensorWindow:
    """Score-gamed starvation of peer ``victim``'s messages for ticks
    ``start <= tick < end``: a hash-chosen ``fraction`` of peers (never
    the victim itself) stop advertising, answering IWANTs for, and
    forwarding messages the victim published — while still receiving
    them and behaving normally on all other traffic."""

    start: int
    end: int
    fraction: float = 0.2
    victim: int = 0


@dataclasses.dataclass(frozen=True)
class StormWindow:
    """Flash-crowd publish storm for ticks ``start <= tick < end``: each
    publisher slot redraws with probability ``skew`` from the ``hot``
    lowest peer ids and publishes to ``topic``."""

    start: int
    end: int
    hot: int = 4
    skew: float = 0.9
    topic: int = 0


@dataclasses.dataclass(frozen=True)
class SlowLinkClass:
    """A heterogeneous link class (permanent, not windowed): a symmetric
    edge hash assigns ``fraction`` of all edges; a member edge's data
    plane opens only every ``period``-th tick (hash-derived phase) and
    additionally drops with probability ``drop`` while open."""

    fraction: float
    period: int = 4
    drop: float = 0.0


@dataclasses.dataclass(frozen=True)
class ChurnWave:
    """Diurnal join/leave schedule: a hash-chosen cohort (``fraction``)
    is dark for the first ``duty`` ticks of every ``period``-tick cycle
    starting at ``phase``, with no new cycle at or after ``until``. Each
    cycle is one expanded outage-like window (:func:`wave_windows`); the
    SAME cohort leaves every cycle (the diurnal pattern)."""

    period: int
    duty: int
    until: int
    fraction: float = 0.25
    phase: int = 0


def wave_windows(w: ChurnWave) -> list:
    """The explicit (start, end) dark windows a :class:`ChurnWave`
    expands to — shared by the batched cut mask and the host injector's
    event schedule so both halves agree tick-for-tick."""
    out = []
    s = w.phase
    while s < w.until:
        out.append((s, s + w.duty))
        s += w.period
    return out


# parse syntax per plan key (the named-error message AND the docs row)
_SYNTAX = {
    "drop": "drop=PROB",
    "dup": "dup=PROB",
    "corrupt": "corrupt=PROB",
    "seed": "seed=INT",
    "partition": "partition=COMPONENTS@START:END",
    "outage": "outage=FRACTION@START:END",
    "eclipse": "eclipse=FRACTION@START:END",
    "censor": "censor=FRACTION[xVICTIM]@START:END",
    "storm": "storm=HOT[xSKEW[xTOPIC]]@START:END",
    "slowlink": "slowlink=FRACTION@PERIOD[:DROP]",
    "wave": "wave=FRACTION@PERIOD:DUTY:UNTIL[:PHASE]",
}


def _window(v: str) -> tuple:
    """``AMT@S:E`` -> (amt_str, start, end), validated."""
    amt, sep, win = v.partition("@")
    s, sep2, e = win.partition(":")
    if not sep or not sep2:
        raise ValueError("missing @START:END window")
    start, end = int(s), int(e)
    if end <= start:
        raise ValueError(f"empty window [{start}, {end})")
    return amt, start, end


def _frac(v: str, what: str = "fraction") -> float:
    f = float(v)
    if not 0.0 <= f <= 1.0:
        raise ValueError(f"{what} {f} outside [0, 1]")
    return f


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Jit-static fault schedule (module docstring). All-defaults is the
    null plan; ``SimConfig.fault_plan=None`` skips the fault pass
    entirely (identical compiled program AND identical RNG stream to a
    plan-free build)."""

    link_drop_prob: float = 0.0
    link_dup_prob: float = 0.0
    corrupt_prob: float = 0.0
    partitions: tuple = ()          # tuple[PartitionWindow, ...]
    outages: tuple = ()             # tuple[OutageWindow, ...]
    eclipses: tuple = ()            # tuple[EclipseWindow, ...]
    censorships: tuple = ()         # tuple[CensorWindow, ...]
    storms: tuple = ()              # tuple[StormWindow, ...]
    slowlinks: tuple = ()           # tuple[SlowLinkClass, ...]
    waves: tuple = ()               # tuple[ChurnWave, ...]
    seed: int = 0

    def active(self) -> bool:
        return (self.link_drop_prob > 0.0 or self.link_dup_prob > 0.0
                or self.corrupt_prob > 0.0 or bool(self.partitions)
                or bool(self.outages) or bool(self.eclipses)
                or bool(self.censorships) or bool(self.storms)
                or bool(self.slowlinks) or bool(self.waves))

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        """Parse the ``GRAFT_FAULT_PLAN`` env-knob syntax: comma-separated
        ``key=value`` items, repeatable for windows/classes.

            drop=0.05,dup=0.01,corrupt=0.1,seed=7
            partition=2@10:30          # 2 components, ticks [10, 30)
            outage=0.2@10:30           # 20% of peers dark, ticks [10, 30)
            eclipse=0.1@10:30          # 10% target region eclipsed
            censor=0.2x5@10:30         # 20% censors starve peer 5's msgs
            storm=8x0.9x1@10:20        # 8 hot publishers, skew .9, topic 1
            slowlink=0.3@4:0.05        # 30% of edges open 1-in-4, drop 5%
            wave=0.25@20:5:60          # 25% dark 5 ticks per 20, until 60

        Malformed items raise a named ``ValueError`` quoting the item and
        its expected syntax; :meth:`format` renders the canonical spec
        back (``FaultPlan.parse(plan.format()) == plan``)."""
        kw: dict = {"partitions": [], "outages": [], "eclipses": [],
                    "censorships": [], "storms": [], "slowlinks": [],
                    "waves": []}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            k, _, v = item.partition("=")
            if k not in _SYNTAX:
                raise ValueError(f"unknown fault-plan item {item!r}; "
                                 f"known keys: {sorted(_SYNTAX)}")
            try:
                if k == "partition":
                    amt, s, e = _window(v)
                    kw["partitions"].append(
                        PartitionWindow(s, e, components=int(amt)))
                elif k == "outage":
                    amt, s, e = _window(v)
                    kw["outages"].append(
                        OutageWindow(s, e, fraction=_frac(amt)))
                elif k == "eclipse":
                    amt, s, e = _window(v)
                    kw["eclipses"].append(
                        EclipseWindow(s, e, fraction=_frac(amt)))
                elif k == "censor":
                    amt, s, e = _window(v)
                    parts = amt.split("x")
                    if len(parts) > 2:
                        raise ValueError("too many x-separated fields")
                    victim = int(parts[1]) if len(parts) == 2 else 0
                    kw["censorships"].append(CensorWindow(
                        s, e, fraction=_frac(parts[0]), victim=victim))
                elif k == "storm":
                    amt, s, e = _window(v)
                    parts = amt.split("x")
                    if len(parts) > 3:
                        raise ValueError("too many x-separated fields")
                    hot = int(parts[0])
                    if hot < 1:
                        raise ValueError(f"hot={hot} must be >= 1")
                    skew = _frac(parts[1], "skew") if len(parts) > 1 else 0.9
                    topic = int(parts[2]) if len(parts) > 2 else 0
                    kw["storms"].append(StormWindow(
                        s, e, hot=hot, skew=skew, topic=topic))
                elif k == "slowlink":
                    amt, _, rest = v.partition("@")
                    if not rest:
                        raise ValueError("missing @PERIOD")
                    p, _, d = rest.partition(":")
                    period = int(p)
                    if period < 1:
                        raise ValueError(f"period={period} must be >= 1")
                    kw["slowlinks"].append(SlowLinkClass(
                        fraction=_frac(amt), period=period,
                        drop=_frac(d, "drop") if d else 0.0))
                elif k == "wave":
                    amt, _, rest = v.partition("@")
                    parts = rest.split(":") if rest else []
                    if len(parts) not in (3, 4):
                        raise ValueError("expected PERIOD:DUTY:UNTIL"
                                         "[:PHASE] after @")
                    period, duty, until = (int(parts[0]), int(parts[1]),
                                           int(parts[2]))
                    phase = int(parts[3]) if len(parts) == 4 else 0
                    if period < 1 or not 0 < duty <= period:
                        raise ValueError(
                            f"need period >= 1 and 0 < duty <= period "
                            f"(got period={period}, duty={duty})")
                    if (until - phase) > 100_000 * period:
                        raise ValueError("wave expands to > 100000 cycles")
                    kw["waves"].append(ChurnWave(
                        period=period, duty=duty, until=until,
                        fraction=_frac(amt), phase=phase))
                elif k == "drop":
                    kw["link_drop_prob"] = _frac(v, "prob")
                elif k == "dup":
                    kw["link_dup_prob"] = _frac(v, "prob")
                elif k == "corrupt":
                    kw["corrupt_prob"] = _frac(v, "prob")
                elif k == "seed":
                    kw["seed"] = int(v)
            except ValueError as err:
                raise ValueError(
                    f"malformed fault-plan item {item!r} (expected "
                    f"{_SYNTAX[k]}): {err}") from err
        for f in ("partitions", "outages", "eclipses", "censorships",
                  "storms", "slowlinks", "waves"):
            kw[f] = tuple(kw[f])
        return FaultPlan(**kw)

    def format(self) -> str:
        """The canonical spec string: ``FaultPlan.parse(p.format()) == p``
        (round-trip pinned by tests/test_adversary.py). Zero-valued knobs
        are omitted; window fields always render fully qualified."""
        items = []
        if self.link_drop_prob:
            items.append(f"drop={self.link_drop_prob!r}")
        if self.link_dup_prob:
            items.append(f"dup={self.link_dup_prob!r}")
        if self.corrupt_prob:
            items.append(f"corrupt={self.corrupt_prob!r}")
        for w in self.partitions:
            items.append(f"partition={w.components}@{w.start}:{w.end}")
        for w in self.outages:
            items.append(f"outage={w.fraction!r}@{w.start}:{w.end}")
        for w in self.eclipses:
            items.append(f"eclipse={w.fraction!r}@{w.start}:{w.end}")
        for w in self.censorships:
            items.append(
                f"censor={w.fraction!r}x{w.victim}@{w.start}:{w.end}")
        for w in self.storms:
            items.append(f"storm={w.hot}x{w.skew!r}x{w.topic}"
                         f"@{w.start}:{w.end}")
        for c in self.slowlinks:
            items.append(f"slowlink={c.fraction!r}@{c.period}:{c.drop!r}")
        for w in self.waves:
            items.append(f"wave={w.fraction!r}@{w.period}:{w.duty}"
                         f":{w.until}:{w.phase}")
        if self.seed:
            items.append(f"seed={self.seed}")
        return ",".join(items)


# ---------------------------------------------------------------------------
# deterministic peer choice shared by both halves


def _mix32_host(x: int) -> int:
    """32-bit integer finalizer (murmur3-style), host ints."""
    x &= 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x45D9F3B) & 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x45D9F3B) & 0xFFFFFFFF
    return x ^ (x >> 16)


def _outage_salt(plan_seed: int, widx: int) -> int:
    return (plan_seed * 0x9E3779B9 + widx * 0x85EBCA6B) & 0xFFFFFFFF


# per-family salt streams: same mixing as outages but a distinct additive
# base per family, so window 0 of two different families never picks the
# same cohort. "outage" keeps base 0 — the historical outage peer choice
# is unchanged (tests pin it across halves).
_FAMILY_SALTS = {
    "outage": (0x85EBCA6B, 0x00000000),
    "censor": (0xC2B2AE35, 0x9E3779B9),
    "wave": (0x27D4EB2F, 0x3C6EF372),
    "slowlink": (0x165667B1, 0xDAA66D2B),
}


def _family_salt(plan_seed: int, family: str, idx: int) -> int:
    mult, base = _FAMILY_SALTS[family]
    return (plan_seed * 0x9E3779B9 + idx * mult + base) & 0xFFFFFFFF


def _thr32(fraction: float) -> int:
    return min(int(fraction * 4294967296.0), 0xFFFFFFFF)


def _hash_mask_host(n: int, salt: int, fraction: float) -> list[bool]:
    thr = _thr32(fraction)
    return [_mix32_host(i ^ salt) < thr for i in range(n)]


def _hash_mask_jax(n: int, salt: int, fraction: float) -> jnp.ndarray:
    x = jnp.arange(n, dtype=U32) ^ U32(salt)
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    x = x ^ (x >> 16)
    return x < U32(_thr32(fraction))


def outage_peers_host(n: int, widx: int, plan: FaultPlan) -> list[bool]:
    """Host-side twin of the in-graph outage choice: peer i is dark in
    outage window ``widx`` iff hash(i, seed, widx) < fraction * 2^32."""
    return _hash_mask_host(n, _outage_salt(plan.seed, widx),
                           plan.outages[widx].fraction)


def _outage_peers_jax(n: int, widx: int, plan: FaultPlan) -> jnp.ndarray:
    return _hash_mask_jax(n, _outage_salt(plan.seed, widx),
                          plan.outages[widx].fraction)


def censor_peers_host(n: int, widx: int, plan: FaultPlan) -> list[bool]:
    """Censor cohort of censorship window ``widx`` (never the victim)."""
    w = plan.censorships[widx]
    mask = _hash_mask_host(n, _family_salt(plan.seed, "censor", widx),
                           w.fraction)
    if 0 <= w.victim < n:
        mask[w.victim] = False
    return mask


def _censor_peers_jax(n: int, widx: int, plan: FaultPlan) -> jnp.ndarray:
    w = plan.censorships[widx]
    mask = _hash_mask_jax(n, _family_salt(plan.seed, "censor", widx),
                          w.fraction)
    return mask & (jnp.arange(n) != w.victim)


def wave_peers_host(n: int, widx: int, plan: FaultPlan) -> list[bool]:
    """The diurnal cohort of wave ``widx`` — the SAME peers every cycle."""
    return _hash_mask_host(n, _family_salt(plan.seed, "wave", widx),
                           plan.waves[widx].fraction)


def _wave_peers_jax(n: int, widx: int, plan: FaultPlan) -> jnp.ndarray:
    return _hash_mask_jax(n, _family_salt(plan.seed, "wave", widx),
                          plan.waves[widx].fraction)


def eclipse_targets_host(n: int, widx: int, plan: FaultPlan,
                         malicious=None) -> list[bool]:
    """Target region of eclipse window ``widx``: honest peers in the
    contiguous id region [0, ceil(fraction*N)). Both halves share this."""
    import math
    w = plan.eclipses[widx]
    lim = max(1, int(math.ceil(w.fraction * n)))
    return [i < lim and not (malicious is not None and malicious[i])
            for i in range(n)]


def _slow_edge_hash_host(i: int, j: int, salt: int) -> int:
    a, b = (i, j) if i < j else (j, i)
    return _mix32_host(((a * 0x9E3779B1) ^ b ^ salt) & 0xFFFFFFFF)


def _slow_edge_hash_jax(neighbors: jnp.ndarray, salt: int,
                        row_start: int = 0,
                        n_global: int | None = None) -> jnp.ndarray:
    """[N, K] symmetric per-edge hash (both directions of an edge hash
    identically — min/max endpoint ordering), matching
    :func:`_slow_edge_hash_host` bit for bit. ``row_start``/``n_global``
    locate a ROW SLICE of a larger graph (degree-bucket views,
    sim/bucketed.py): row r holds global peer id row_start + r and
    neighbor ids stay global, so the hash word per edge is identical to
    the full-graph call's."""
    n = n_global if n_global is not None else neighbors.shape[0]
    i = jnp.broadcast_to(
        (row_start + jnp.arange(neighbors.shape[0])).astype(U32)[:, None],
        neighbors.shape)
    j = jnp.clip(neighbors, 0, n - 1).astype(U32)
    a = jnp.minimum(i, j)
    b = jnp.maximum(i, j)
    x = ((a * U32(0x9E3779B1)) ^ b ^ U32(salt))
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    return x ^ (x >> 16)


# ---------------------------------------------------------------------------
# schedule introspection (journal headers, dashboard, recovery censuses)


def attack_schedule(plan) -> list:
    """The plan's attack/workload schedule as plain dicts — what the
    health-journal run header stamps (sim/telemetry.py) and the dashboard
    renders. Windowed families carry ``start``/``end``; slow-link classes
    are permanent (``end`` is None)."""
    out: list = []
    if plan is None:
        return out
    for w in plan.partitions:
        out.append({"kind": "partition", "start": w.start, "end": w.end,
                    "components": w.components})
    for w in plan.outages:
        out.append({"kind": "outage", "start": w.start, "end": w.end,
                    "fraction": w.fraction})
    for w in plan.eclipses:
        out.append({"kind": "eclipse", "start": w.start, "end": w.end,
                    "fraction": w.fraction})
    for w in plan.censorships:
        out.append({"kind": "censor", "start": w.start, "end": w.end,
                    "fraction": w.fraction, "victim": w.victim})
    for w in plan.storms:
        out.append({"kind": "storm", "start": w.start, "end": w.end,
                    "hot": w.hot, "skew": w.skew, "topic": w.topic})
    for c in plan.slowlinks:
        out.append({"kind": "slowlink", "start": 0, "end": None,
                    "fraction": c.fraction, "period": c.period,
                    "drop": c.drop})
    for i, w in enumerate(plan.waves):
        for s, e in wave_windows(w):
            out.append({"kind": "wave", "start": s, "end": e, "wave": i,
                        "fraction": w.fraction})
    return sorted(out, key=lambda d: (d["start"], d["kind"]))


def attack_end_tick(plan) -> int:
    """The tick the plan's last scheduled attack window closes (0 for a
    window-free plan) — the heal tick a recovery census must anchor on
    (scripts/sweep_scores.py; the hardcoded-20 bug class of PR 7).
    Permanent slow-link classes have no end and do not move it."""
    if plan is None:
        return 0
    ends = [w.end for fam in (plan.partitions, plan.outages, plan.eclipses,
                              plan.censorships, plan.storms) for w in fam]
    for w in plan.waves:
        wins = wave_windows(w)
        if wins:
            ends.append(wins[-1][1])
    return max(ends) if ends else 0


# ---------------------------------------------------------------------------
# batched half: the per-tick fault pass


class FaultTick(NamedTuple):
    """What engine.step threads through the rest of the tick."""

    want_down: jnp.ndarray          # [N, K] bool: edges the plan holds down
    link_ok: jnp.ndarray | None     # [N, K] bool data admission (drop), or None
    dup_edges: jnp.ndarray | None   # [N, K] bool duplicating edges, or None
    corrupt: jnp.ndarray | None     # [P] bool corrupted publishes, or None
    injected: jnp.ndarray           # uint32 scalar: fault bits fired this tick


def edge_cut_mask(plan: FaultPlan, tick: jnp.ndarray,
                  neighbors: jnp.ndarray, reverse_slot: jnp.ndarray,
                  disconnect_tick: jnp.ndarray | None = None,
                  malicious: jnp.ndarray | None = None,
                  row_start: int = 0, n_global: int | None = None
                  ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(want_down [N,K], heal_mask [N,K], injected uint32) for this tick's
    partition/outage schedule. ``heal_mask`` covers exactly the edges the
    PLAN took down: each window's cut set is a pure function of peer ids,
    and an edge counts as plan-downed iff SOME window covering it was
    active at its ``disconnect_tick`` (take_edges_down stamps the cut
    tick; an edge already down before every covering window opened was
    downed by ordinary churn and stays on the churn/PX reconnect path).
    The any-covering-window formulation matters for back-to-back or
    overlapping windows over the same edges: the later window inherits
    the earlier window's cut (the edge is already down, so its stamp
    predates the later start) and must still heal it at its own end —
    the host injector's keep-severed-until-no-window-cuts-it bookkeeping
    (``HostFaultInjector._reknit``), mirrored. Symmetric by construction
    (component membership, peer-outage, eclipse-target/honest membership,
    and the disconnect stamp are all edge-symmetric), so RemovePeer
    semantics stay edge-symmetric. ``malicious`` gates the eclipse cut
    (sybil edges are the ones an eclipse deliberately leaves standing);
    eclipse windows in a plan require it.

    ``row_start``/``n_global`` locate a ROW SLICE of a larger graph
    (degree-bucket views, sim/bucketed.py): peer-membership predicates
    are evaluated on GLOBAL ids (row r is peer row_start + r; neighbor
    ids are global; ``malicious`` must be the GLOBAL [n_global] mask),
    so per-bucket masks concat into exactly the full-graph call's."""
    import math

    from .invariants import (FAULT_ECLIPSE, FAULT_OUTAGE, FAULT_PARTITION,
                             FAULT_WAVE)

    nrows, k = neighbors.shape
    n = n_global if n_global is not None else nrows
    # row-window restriction of a global [N] peer predicate. The dense
    # call (the default) keeps the identity — NOT an identity slice op —
    # so pre-bucketing programs stay byte-identical in HLO
    if row_start == 0 and nrows == n:
        def rsl(a):
            return a
    else:
        def rsl(a):
            return jax.lax.slice_in_dim(a, row_start, row_start + nrows)
    known = (neighbors >= 0) & (reverse_slot >= 0)
    nbr = jnp.clip(neighbors, 0, n - 1)

    wins = []                   # (start, end, cut set, injected bit)
    for w in plan.partitions:
        comp = jnp.arange(n, dtype=jnp.int32) % w.components
        cross = (rsl(comp)[:, None] != comp[nbr]) & known
        wins.append((w.start, w.end, cross, FAULT_PARTITION))
    for i, w in enumerate(plan.outages):
        dark = _outage_peers_jax(n, i, plan)
        wins.append((w.start, w.end,
                     (rsl(dark)[:, None] | dark[nbr]) & known, FAULT_OUTAGE))
    if plan.eclipses and malicious is None:
        raise ValueError("edge_cut_mask: a plan with eclipse windows "
                         "needs the malicious mask (sybil edges are the "
                         "ones the eclipse leaves standing)")
    for w in plan.eclipses:
        lim = max(1, int(math.ceil(w.fraction * n)))
        tgt = (jnp.arange(n) < lim) & ~malicious
        honest2 = rsl(~malicious)[:, None] & ~malicious[nbr]
        cross = (rsl(tgt)[:, None] ^ tgt[nbr]) & honest2 & known
        wins.append((w.start, w.end, cross, FAULT_ECLIPSE))
    for i, w in enumerate(plan.waves):
        dark = _wave_peers_jax(n, i, plan)
        cut = (rsl(dark)[:, None] | dark[nbr]) & known
        for s, e in wave_windows(w):
            wins.append((s, e, cut, FAULT_WAVE))

    cut = jnp.zeros((nrows, k), bool)
    heal = jnp.zeros((nrows, k), bool)
    inj = U32(0)
    # plan-downed: the edge's disconnect stamp falls inside SOME window
    # that cuts it (true everywhere when no stamps are supplied)
    if disconnect_tick is None:
        plan_downed = jnp.ones((nrows, k), bool)
    else:
        plan_downed = jnp.zeros((nrows, k), bool)
        for s, e, cs, _ in wins:
            plan_downed = plan_downed | \
                (cs & (disconnect_tick >= s) & (disconnect_tick < e))
    for s, e, cs, bit in wins:
        act = (tick >= s) & (tick < e)
        cut = cut | (act & cs)
        heal = heal | ((tick == e) & cs & plan_downed)
        inj = inj | jnp.where(act, U32(bit), U32(0))
    return cut, heal, inj


def apply_faults(state: SimState, cfg: SimConfig, tp: TopicParams,
                 key: jax.Array) -> tuple[SimState, FaultTick]:
    """The start-of-tick fault pass: apply partition/outage transitions
    (down with RemovePeer semantics, up through the reconnect/retention
    path) and draw this tick's link/corruption faults."""
    from ..ops.churn import bring_edges_up, take_edges_down
    from .invariants import FAULT_LINK_DROP, FAULT_LINK_DUP

    plan = cfg.fault_plan
    n, k = state.neighbors.shape
    if plan.slowlinks:
        # the extra split only exists under a slow-link plan, so every
        # pre-existing plan shape keeps its exact historical RNG stream
        kd, kdup, kc, kslow = jax.random.split(key, 4)
    else:
        kd, kdup, kc = jax.random.split(key, 3)
        kslow = None

    if plan.partitions or plan.outages or plan.eclipses or plan.waves:
        # want_down from PRE-take-down state; heal_mask consults the
        # disconnect stamps as they stand at the window's end (the cut
        # itself stamped them >= window.start)
        want_down, heal_mask, inj = edge_cut_mask(
            plan, state.tick, state.neighbors, state.reverse_slot,
            disconnect_tick=state.disconnect_tick,
            malicious=state.malicious)
        go_down = state.connected & want_down
        state = take_edges_down(state, cfg, tp, go_down)
        # heal redials exactly the ending windows' own cuts (edges a
        # still-active window wants down stay down); down edges outside
        # any cut set remain on the ordinary churn/PX reconnect path
        come_up = heal_mask & ~state.connected & ~want_down
        state = bring_edges_up(state, cfg, come_up)
    else:
        want_down, _, inj = edge_cut_mask(
            plan, state.tick, state.neighbors, state.reverse_slot,
            malicious=state.malicious)

    # workload-family activity bits (the cut families stamp theirs in
    # edge_cut_mask; storms/censorships act elsewhere — publisher choice
    # and the forwarding word masks — but their ACTIVE windows are
    # schedule facts, recorded here like a partition window's)
    from .invariants import FAULT_CENSOR, FAULT_STORM
    for w in plan.storms:
        inj = inj | jnp.where((state.tick >= w.start) & (state.tick < w.end),
                              U32(FAULT_STORM), U32(0))
    for w in plan.censorships:
        inj = inj | jnp.where((state.tick >= w.start) & (state.tick < w.end),
                              U32(FAULT_CENSOR), U32(0))

    valid = state.connected
    link_ok = dup_edges = corrupt = None
    if plan.link_drop_prob > 0.0:
        link_ok = jax.random.uniform(kd, (n, k)) >= plan.link_drop_prob
        inj = inj | jnp.where(jnp.any(~link_ok & valid),
                              U32(FAULT_LINK_DROP), U32(0))
    if plan.slowlinks:
        # heterogeneous link classes: a member edge's data plane opens
        # only every period-th tick (hash-derived phase) and drops with
        # cl.drop while open — layered INTO link_ok like the uniform drop
        from .invariants import FAULT_SLOWLINK
        kss = jax.random.split(kslow, len(plan.slowlinks))
        lk = jnp.ones((n, k), bool)
        stalled = jnp.zeros((), bool)
        known = state.neighbors >= 0
        for ci, cl in enumerate(plan.slowlinks):
            h = _slow_edge_hash_jax(
                state.neighbors, _family_salt(plan.seed, "slowlink", ci))
            member = (h < U32(_thr32(cl.fraction))) & known
            phase = (h % U32(cl.period)).astype(jnp.int32)
            open_now = ((state.tick + phase) % cl.period) == 0
            ok = open_now
            if cl.drop > 0.0:
                ok = ok & (jax.random.uniform(kss[ci], (n, k)) >= cl.drop)
            lk = lk & (~member | ok)
            stalled = stalled | jnp.any(member & ~open_now & valid)
        link_ok = lk if link_ok is None else (link_ok & lk)
        inj = inj | jnp.where(stalled, U32(FAULT_SLOWLINK), U32(0))
    if plan.link_dup_prob > 0.0:
        dup_edges = (jax.random.uniform(kdup, (n, k)) < plan.link_dup_prob) \
            & valid
        inj = inj | jnp.where(jnp.any(dup_edges), U32(FAULT_LINK_DUP), U32(0))
    if plan.corrupt_prob > 0.0:
        corrupt = jax.random.uniform(
            kc, (cfg.publishers_per_tick,)) < plan.corrupt_prob
        # FAULT_CORRUPT is NOT set here: whether a draw corrupts anything
        # depends on who publishes (malicious publishers are already
        # invalid) — engine.step sets the bit from the EFFECTIVE
        # corruption after choose_publishers
    return state, FaultTick(want_down=want_down, link_ok=link_ok,
                            dup_edges=dup_edges, corrupt=corrupt,
                            injected=inj)


# ---------------------------------------------------------------------------
# workload-family hooks the engine consumes (sim/engine.py)


def storm_publishers(state: SimState, cfg: SimConfig, peers: jnp.ndarray,
                     topics: jnp.ndarray, key: jax.Array
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply the plan's active :class:`StormWindow`\\ s to this tick's
    publisher draw: with probability ``skew`` a publisher slot redraws
    from the ``hot`` lowest peer ids and publishes to the storm topic.
    Called by ``engine.choose_publishers`` only when storms exist, so
    storm-free configs keep the exact historical RNG stream."""
    plan = cfg.fault_plan
    for w in plan.storms:
        key, kh, ks = jax.random.split(key, 3)
        active = (state.tick >= w.start) & (state.tick < w.end)
        hot = jax.random.randint(kh, peers.shape, 0, min(w.hot, cfg.n_peers))
        use = active & (jax.random.uniform(ks, peers.shape) < w.skew)
        peers = jnp.where(use, hot, peers)
        topics = jnp.where(use, jnp.int32(w.topic), topics)
    return peers, topics


def censor_word_mask(state: SimState, cfg: SimConfig) -> jnp.ndarray | None:
    """[W, N] packed word mask of the message slots peer ``n`` SUPPRESSES
    this tick under the plan's active :class:`CensorWindow`\\ s (no IHAVE,
    no IWANT answer, no forward — receiving is unaffected), or None when
    no censorship is configured. Computed AFTER publish (engine.step) so
    the victim's brand-new messages are covered the tick they appear."""
    plan = cfg.fault_plan
    if plan is None or not plan.censorships:
        return None
    from ..ops.bits import pack_bool
    n = state.neighbors.shape[0]
    mask = None
    for i, w in enumerate(plan.censorships):
        active = (state.tick >= w.start) & (state.tick < w.end)
        vic = pack_bool(((state.msg_publisher == w.victim)
                         & (state.msg_topic >= 0))[None, :])[0]     # [W]
        cens = _censor_peers_jax(n, i, plan)                        # [N]
        mw = jnp.where(active & cens[None, :], vic[:, None], U32(0))
        mask = mw if mask is None else (mask | mw)
    return mask


def attacker_mask(state: SimState, cfg: SimConfig) -> jnp.ndarray:
    """[N] bool: the peers the telemetry graylist split counts as
    ATTACKERS — sybil actors (``state.malicious``) plus every censor
    cohort of the plan (window-independent: the census asks "is this peer
    an adversary", not "is it attacking right now"). The score-response
    contract (sim/adversary.py) reads the split this mask induces."""
    att = state.malicious
    plan = getattr(cfg, "fault_plan", None)
    if plan is not None:
        n = state.malicious.shape[0]
        for i in range(len(plan.censorships)):
            att = att | _censor_peers_jax(n, i, plan)
    return att


# ---------------------------------------------------------------------------
# host half: the same plan on the discrete-event runtime


class HostFaultInjector:
    """Install a :class:`FaultPlan` on a functional-runtime swarm.

    Mirrors the batched semantics on net/network.py primitives: partitions
    and outages DISCONNECT the affected host pairs at window start
    (notifiee fan-out fires RemovePeer in every PubSub, pubsub.go:711-757)
    and re-``connect`` them at window end; link drop/duplication ride the
    ``Network.link_fault`` hook consulted by ``Host.send``. One tick of
    the batched engine corresponds to one second of scheduler time (the
    1 tick == 1 s == 1 heartbeat quantization, SURVEY.md §7 "Time").

    ``corrupt_prob`` has no host-side hook here: on the runtime, corrupt
    traffic is expressed through topic validators (the reference's own
    mechanism) — see tests/test_adversarial_runtime.py. The same applies
    to ``censorships`` and ``storms``: on the host half a censor is a
    router/validator behavior and a storm is the scenario's own publish
    schedule, so the injector carries only the CONNECTION-layer families
    (partitions, outages, eclipses, waves) and the LINK-layer ones
    (drop/dup/slowlink).

    ORDERING CONTRACT: ``hosts`` must be in engine row order — list
    position i IS peer row i of the batched half (partition components
    are ``i % components``, outage/wave peers hash the row id, and
    eclipse targets are the low-id region on both sides). Build the swarm
    the way topology.from_hosts expects and pass the same list; any other
    order silently picks different cut/dark sets than the batched run of
    the same plan. ``malicious`` (row-ordered bools) is required when the
    plan has eclipse windows — the eclipse leaves sybil edges standing.
    """

    def __init__(self, network, hosts, plan: FaultPlan, malicious=None):
        import random as _random

        self.network = network
        self.hosts = list(hosts)
        self.plan = plan
        self.malicious = list(malicious) if malicious is not None else None
        if plan.eclipses and self.malicious is None:
            raise ValueError("HostFaultInjector: a plan with eclipse "
                             "windows needs the malicious list (engine "
                             "row order)")
        self.rng = _random.Random(plan.seed)
        self.index = {h.peer_id: i for i, h in enumerate(self.hosts)}
        self._partitions_live: list[PartitionWindow] = []
        self._eclipse_targets: dict = {}     # widx -> [bool] target rows
        self._dark: dict = {}                # (family, widx) -> set(peer ids)
        self._severed: list = []             # [(host_a, host_b)]
        network.link_fault = self._link_fault
        sched = network.scheduler
        now = sched.now()
        for w in plan.partitions:
            sched.call_at(max(now, float(w.start)),
                          lambda w=w: self._partition_start(w))
            sched.call_at(max(now, float(w.end)),
                          lambda w=w: self._partition_end(w))
        for i, w in enumerate(plan.outages):
            sched.call_at(max(now, float(w.start)),
                          lambda i=i, w=w: self._outage_start(i, w))
            sched.call_at(max(now, float(w.end)),
                          lambda i=i: self._outage_end(i))
        for i, w in enumerate(plan.eclipses):
            sched.call_at(max(now, float(w.start)),
                          lambda i=i, w=w: self._eclipse_start(i, w))
            sched.call_at(max(now, float(w.end)),
                          lambda i=i: self._eclipse_end(i))
        for i, w in enumerate(plan.waves):
            # one scheduled (start, end) pair per expanded cycle — the
            # batched half's wave_windows expansion, mirrored exactly
            for s, e in wave_windows(w):
                sched.call_at(max(now, float(s)),
                              lambda i=i: self._wave_start(i))
                sched.call_at(max(now, float(e)),
                              lambda i=i: self._wave_end(i))

    # -- the one cut predicate (all transitions and the link hook agree) --

    def _is_dark(self, pid) -> bool:
        return any(pid in dark for dark in self._dark.values())

    def _is_cut(self, i: int, j: int) -> bool:
        for w in self._partitions_live:
            if i % w.components != j % w.components:
                return True
        for tgt in self._eclipse_targets.values():
            if (tgt[i] != tgt[j]) and not (
                    self.malicious[i] or self.malicious[j]):
                return True
        return self._is_dark(self.hosts[i].peer_id) \
            or self._is_dark(self.hosts[j].peer_id)

    # -- link hook (Host.send) --

    def _link_fault(self, src, dst, has_data: bool = True) -> str:
        i, j = self.index.get(src), self.index.get(dst)
        if i is None or j is None:
            return "ok"
        if self._is_cut(i, j):
            return "drop"             # cut/dark link: nothing crosses
        # slow-link classes: a member edge's DATA plane opens only every
        # period-th scheduler second ((tick + phase) % period == 0, the
        # batched half's formula on the same symmetric edge hash) and
        # drops with cl.drop even when open — control always flows
        if self.plan.slowlinks and has_data:
            tick = int(self.network.scheduler.now())
            for ci, cl in enumerate(self.plan.slowlinks):
                h = _slow_edge_hash_host(
                    i, j, _family_salt(self.plan.seed, "slowlink", ci))
                if h >= _thr32(cl.fraction):
                    continue
                if (tick + h % cl.period) % cl.period != 0:
                    return "drop_data"
                if cl.drop > 0.0 and self.rng.random() < cl.drop:
                    return "drop_data"
        # lossy links shed the DATA plane only (batched-half parity:
        # forward_tick masks link_ok into data_ok, control still flows),
        # so the drop draw is only spent on data-bearing frames
        if self.plan.link_drop_prob > 0.0 and has_data \
                and self.rng.random() < self.plan.link_drop_prob:
            return "drop_data"
        # duplication likewise only models retransmitted DATA frames (the
        # batched dup_offer re-offers recent deliveries on mesh edges);
        # doubling a control frame (GRAFT handled twice) would be a fault
        # class the batched half cannot mirror
        if self.plan.link_dup_prob > 0.0 and has_data \
                and self.rng.random() < self.plan.link_dup_prob:
            return "dup"
        return "ok"

    # -- window transitions --

    def _sever_cut(self) -> None:
        """Disconnect every currently-connected pair the cut predicate now
        covers (called after a window opens)."""
        for a in self.hosts:
            ia = self.index[a.peer_id]
            for pid in list(a.conns):
                ib = self.index.get(pid)
                if ib is not None and self._is_cut(ia, ib):
                    a.disconnect(pid)
                    self._severed.append((a, self.hosts[ib]))

    def _reknit(self) -> None:
        """Reconnect severed pairs no longer covered by ANY active window
        (called after a window closes); pairs another window still cuts
        stay severed until that window too ends — matching the batched
        half's per-window heal_mask & ~want_down semantics."""
        keep = []
        for a, b in self._severed:
            if self._is_cut(self.index[a.peer_id], self.index[b.peer_id]):
                keep.append((a, b))
            else:
                a.connect(b)
        self._severed = keep

    def _partition_start(self, w: PartitionWindow) -> None:
        self._partitions_live.append(w)
        self._sever_cut()

    def _partition_end(self, w: PartitionWindow) -> None:
        if w in self._partitions_live:
            self._partitions_live.remove(w)
        self._reknit()

    def _outage_start(self, widx: int, w: OutageWindow) -> None:
        dark_mask = outage_peers_host(len(self.hosts), widx, self.plan)
        self._dark[("outage", widx)] = \
            {h.peer_id for h, d in zip(self.hosts, dark_mask) if d}
        self._sever_cut()

    def _outage_end(self, widx: int) -> None:
        self._dark.pop(("outage", widx), None)
        self._reknit()

    def _eclipse_start(self, widx: int, w: EclipseWindow) -> None:
        self._eclipse_targets[widx] = eclipse_targets_host(
            len(self.hosts), widx, self.plan, malicious=self.malicious)
        self._sever_cut()

    def _eclipse_end(self, widx: int) -> None:
        self._eclipse_targets.pop(widx, None)
        self._reknit()

    def _wave_start(self, widx: int) -> None:
        dark_mask = wave_peers_host(len(self.hosts), widx, self.plan)
        self._dark[("wave", widx)] = \
            {h.peer_id for h, d in zip(self.hosts, dark_mask) if d}
        self._sever_cut()

    def _wave_end(self, widx: int) -> None:
        self._dark.pop(("wave", widx), None)
        self._reknit()
