"""Declarative fault injection for both halves of the framework.

The reference router's whole reason for existing (gossipsub v1.1, SURVEY.md
§2 scoring P1-P7) is behavior under hostile and DEGRADED networks, yet the
engine could only exercise the failure modes baked into the five BASELINE
scenarios. A ``FaultPlan`` is a jit-static description of what goes wrong
and when, applied every tick by ``sim/engine.step`` (batched half) or
installed on the discrete-event scheduler by :class:`HostFaultInjector`
(functional-runtime half, via the ``Network.link_fault`` hook in
net/network.py) — the SAME plan runs against both halves, so recovery
behavior (partition heal, outage return, mesh self-healing time) can be
parity-checked between them.

Fault classes:

- **link drop** (``link_drop_prob``): each tick, each directed edge loses
  its DATA plane with this probability — eager forwards, flood publishes,
  and IWANT-pull answers on the edge vanish in flight. Control traffic
  (GRAFT/PRUNE/IHAVE) still flows, like the peer gater's RED drops
  (peer_gater.go:320-363 strips data, keeps control): the batched
  exchange's edge symmetry must hold, and real links drop big data frames
  long before tiny control frames. A link-eaten pull answer IS charged as
  a broken promise: the promise tracker fires on non-delivery at expiry
  whatever the cause (gossip_tracer.go:79-115; the host half's tracer
  behaves the same), so P7 scoring stays parity-comparable between
  halves under a drop plan.
- **link duplication** (``link_dup_prob``): each tick, a duplicating mesh
  edge re-offers its recent deliveries (the mcache gossip slice) alongside
  the frontier — seen-cache hits count as mesh duplicates (P3 credit,
  score.go:949-981) and gater duplicates, exactly where a re-transmitted
  RPC would land in the reference.
- **partitions** (``partitions``): on a tick schedule, peers split into
  ``components`` by ``peer_id % components``; cross-component edges go
  DOWN with full RemovePeer semantics (ops/churn.take_edges_down —
  pubsub.go:711-757 dead-peer path, score retention per score.go:611-644)
  and come back at the window's ``end`` tick through the reconnect path
  (retention expiry included), so mesh self-healing and backoff are
  genuinely exercised, not simulated around.
- **regional outages** (``outages``): a deterministic pseudo-random
  ``fraction`` of peers goes completely dark for the window (all their
  edges down, RemovePeer semantics), then returns through the same
  churn/backoff/retention path. Peer choice uses a shared integer hash
  (:func:`outage_peers`) so the batched and host halves pick the SAME
  peers.
- **corruption** (``corrupt_prob``): each honest publish draws this
  probability of being corrupted in flight — honest receivers REJECT it
  and charge P4 invalid-message deliveries (score.go:899-918), feeding the
  scoring pipeline invalid traffic that no sybil actor sent.

Every random draw is keyed off the step key (batched) or a
``random.Random(plan.seed)`` stream (host), so runs are reproducible; the
plan itself is a frozen dataclass, hashable, and lives on ``SimConfig`` as
a jit-static field — a plan change recompiles, a key change replays.

Which faults fired is recorded per tick into ``SimState.fault_flags``
(sim/invariants.py bit layout), making every degraded run self-identifying
in bench lines and trace exports.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .config import SimConfig, TopicParams
from .state import SimState

U32 = jnp.uint32


# ---------------------------------------------------------------------------
# the plan


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """Split the network into ``components`` (peer_id % components) for
    ticks ``start <= tick < end``; heal (redial the cut edges) at
    ``end``."""

    start: int
    end: int
    components: int = 2


@dataclasses.dataclass(frozen=True)
class OutageWindow:
    """A ``fraction`` of peers goes completely dark for ticks
    ``start <= tick < end``, returning at ``end`` through the reconnect
    path. Peer choice is :func:`outage_peers` (shared across halves)."""

    start: int
    end: int
    fraction: float = 0.1


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Jit-static fault schedule (module docstring). All-defaults is the
    null plan; ``SimConfig.fault_plan=None`` skips the fault pass
    entirely (identical compiled program AND identical RNG stream to a
    plan-free build)."""

    link_drop_prob: float = 0.0
    link_dup_prob: float = 0.0
    corrupt_prob: float = 0.0
    partitions: tuple = ()          # tuple[PartitionWindow, ...]
    outages: tuple = ()             # tuple[OutageWindow, ...]
    seed: int = 0

    def active(self) -> bool:
        return (self.link_drop_prob > 0.0 or self.link_dup_prob > 0.0
                or self.corrupt_prob > 0.0 or bool(self.partitions)
                or bool(self.outages))

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        """Parse the ``GRAFT_FAULT_PLAN`` env-knob syntax: comma-separated
        ``key=value`` items, repeatable for windows.

            drop=0.05,dup=0.01,corrupt=0.1,seed=7
            partition=2@10:30          # 2 components, ticks [10, 30)
            outage=0.2@10:30           # 20% of peers dark, ticks [10, 30)
        """
        kw: dict = {"partitions": [], "outages": []}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            k, _, v = item.partition("=")
            if k == "partition":
                amt, _, win = v.partition("@")
                s, _, e = win.partition(":")
                kw["partitions"].append(
                    PartitionWindow(int(s), int(e), components=int(amt)))
            elif k == "outage":
                amt, _, win = v.partition("@")
                s, _, e = win.partition(":")
                kw["outages"].append(
                    OutageWindow(int(s), int(e), fraction=float(amt)))
            elif k == "drop":
                kw["link_drop_prob"] = float(v)
            elif k == "dup":
                kw["link_dup_prob"] = float(v)
            elif k == "corrupt":
                kw["corrupt_prob"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            else:
                raise ValueError(f"unknown fault-plan item {item!r}")
        kw["partitions"] = tuple(kw["partitions"])
        kw["outages"] = tuple(kw["outages"])
        return FaultPlan(**kw)


# ---------------------------------------------------------------------------
# deterministic peer choice shared by both halves


def _mix32_host(x: int) -> int:
    """32-bit integer finalizer (murmur3-style), host ints."""
    x &= 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x45D9F3B) & 0xFFFFFFFF
    x = ((x ^ (x >> 16)) * 0x45D9F3B) & 0xFFFFFFFF
    return x ^ (x >> 16)


def _outage_salt(plan_seed: int, widx: int) -> int:
    return (plan_seed * 0x9E3779B9 + widx * 0x85EBCA6B) & 0xFFFFFFFF


def outage_peers_host(n: int, widx: int, plan: FaultPlan) -> list[bool]:
    """Host-side twin of the in-graph outage choice: peer i is dark in
    outage window ``widx`` iff hash(i, seed, widx) < fraction * 2^32."""
    w = plan.outages[widx]
    thr = min(int(w.fraction * 4294967296.0), 0xFFFFFFFF)
    salt = _outage_salt(plan.seed, widx)
    return [_mix32_host(i ^ salt) < thr for i in range(n)]


def _outage_peers_jax(n: int, widx: int, plan: FaultPlan) -> jnp.ndarray:
    w = plan.outages[widx]
    thr = U32(min(int(w.fraction * 4294967296.0), 0xFFFFFFFF))
    x = jnp.arange(n, dtype=U32) ^ U32(_outage_salt(plan.seed, widx))
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    x = (x ^ (x >> 16)) * U32(0x45D9F3B)
    x = x ^ (x >> 16)
    return x < thr


# ---------------------------------------------------------------------------
# batched half: the per-tick fault pass


class FaultTick(NamedTuple):
    """What engine.step threads through the rest of the tick."""

    want_down: jnp.ndarray          # [N, K] bool: edges the plan holds down
    link_ok: jnp.ndarray | None     # [N, K] bool data admission (drop), or None
    dup_edges: jnp.ndarray | None   # [N, K] bool duplicating edges, or None
    corrupt: jnp.ndarray | None     # [P] bool corrupted publishes, or None
    injected: jnp.ndarray           # uint32 scalar: fault bits fired this tick


def edge_cut_mask(plan: FaultPlan, tick: jnp.ndarray,
                  neighbors: jnp.ndarray, reverse_slot: jnp.ndarray,
                  disconnect_tick: jnp.ndarray | None = None
                  ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(want_down [N,K], heal_mask [N,K], injected uint32) for this tick's
    partition/outage schedule. ``heal_mask`` covers exactly the edges the
    PLAN took down: each window's cut set is a pure function of peer ids,
    and an edge counts as plan-downed iff SOME window covering it was
    active at its ``disconnect_tick`` (take_edges_down stamps the cut
    tick; an edge already down before every covering window opened was
    downed by ordinary churn and stays on the churn/PX reconnect path).
    The any-covering-window formulation matters for back-to-back or
    overlapping windows over the same edges: the later window inherits
    the earlier window's cut (the edge is already down, so its stamp
    predates the later start) and must still heal it at its own end —
    the host injector's keep-severed-until-no-window-cuts-it bookkeeping
    (``HostFaultInjector._reknit``), mirrored. Symmetric by construction
    (component membership, peer-outage, and the disconnect stamp are all
    edge-symmetric), so RemovePeer semantics stay edge-symmetric."""
    from .invariants import FAULT_OUTAGE, FAULT_PARTITION

    n, k = neighbors.shape
    known = (neighbors >= 0) & (reverse_slot >= 0)
    nbr = jnp.clip(neighbors, 0, n - 1)

    wins = []                   # (start, end, cut set, injected bit)
    for w in plan.partitions:
        comp = jnp.arange(n, dtype=jnp.int32) % w.components
        cross = (comp[:, None] != comp[nbr]) & known
        wins.append((w.start, w.end, cross, FAULT_PARTITION))
    for i, w in enumerate(plan.outages):
        dark = _outage_peers_jax(n, i, plan)
        wins.append((w.start, w.end,
                     (dark[:, None] | dark[nbr]) & known, FAULT_OUTAGE))

    cut = jnp.zeros((n, k), bool)
    heal = jnp.zeros((n, k), bool)
    inj = U32(0)
    # plan-downed: the edge's disconnect stamp falls inside SOME window
    # that cuts it (true everywhere when no stamps are supplied)
    if disconnect_tick is None:
        plan_downed = jnp.ones((n, k), bool)
    else:
        plan_downed = jnp.zeros((n, k), bool)
        for s, e, cs, _ in wins:
            plan_downed = plan_downed | \
                (cs & (disconnect_tick >= s) & (disconnect_tick < e))
    for s, e, cs, bit in wins:
        act = (tick >= s) & (tick < e)
        cut = cut | (act & cs)
        heal = heal | ((tick == e) & cs & plan_downed)
        inj = inj | jnp.where(act, U32(bit), U32(0))
    return cut, heal, inj


def apply_faults(state: SimState, cfg: SimConfig, tp: TopicParams,
                 key: jax.Array) -> tuple[SimState, FaultTick]:
    """The start-of-tick fault pass: apply partition/outage transitions
    (down with RemovePeer semantics, up through the reconnect/retention
    path) and draw this tick's link/corruption faults."""
    from ..ops.churn import bring_edges_up, take_edges_down
    from .invariants import FAULT_LINK_DROP, FAULT_LINK_DUP

    plan = cfg.fault_plan
    n, k = state.neighbors.shape
    kd, kdup, kc = jax.random.split(key, 3)

    if plan.partitions or plan.outages:
        # want_down from PRE-take-down state; heal_mask consults the
        # disconnect stamps as they stand at the window's end (the cut
        # itself stamped them >= window.start)
        want_down, heal_mask, inj = edge_cut_mask(
            plan, state.tick, state.neighbors, state.reverse_slot,
            disconnect_tick=state.disconnect_tick)
        go_down = state.connected & want_down
        state = take_edges_down(state, cfg, tp, go_down)
        # heal redials exactly the ending windows' own cuts (edges a
        # still-active window wants down stay down); down edges outside
        # any cut set remain on the ordinary churn/PX reconnect path
        come_up = heal_mask & ~state.connected & ~want_down
        state = bring_edges_up(state, cfg, come_up)
    else:
        want_down, _, inj = edge_cut_mask(
            plan, state.tick, state.neighbors, state.reverse_slot)

    valid = state.connected
    link_ok = dup_edges = corrupt = None
    if plan.link_drop_prob > 0.0:
        link_ok = jax.random.uniform(kd, (n, k)) >= plan.link_drop_prob
        inj = inj | jnp.where(jnp.any(~link_ok & valid),
                              U32(FAULT_LINK_DROP), U32(0))
    if plan.link_dup_prob > 0.0:
        dup_edges = (jax.random.uniform(kdup, (n, k)) < plan.link_dup_prob) \
            & valid
        inj = inj | jnp.where(jnp.any(dup_edges), U32(FAULT_LINK_DUP), U32(0))
    if plan.corrupt_prob > 0.0:
        corrupt = jax.random.uniform(
            kc, (cfg.publishers_per_tick,)) < plan.corrupt_prob
        # FAULT_CORRUPT is NOT set here: whether a draw corrupts anything
        # depends on who publishes (malicious publishers are already
        # invalid) — engine.step sets the bit from the EFFECTIVE
        # corruption after choose_publishers
    return state, FaultTick(want_down=want_down, link_ok=link_ok,
                            dup_edges=dup_edges, corrupt=corrupt,
                            injected=inj)


# ---------------------------------------------------------------------------
# host half: the same plan on the discrete-event runtime


class HostFaultInjector:
    """Install a :class:`FaultPlan` on a functional-runtime swarm.

    Mirrors the batched semantics on net/network.py primitives: partitions
    and outages DISCONNECT the affected host pairs at window start
    (notifiee fan-out fires RemovePeer in every PubSub, pubsub.go:711-757)
    and re-``connect`` them at window end; link drop/duplication ride the
    ``Network.link_fault`` hook consulted by ``Host.send``. One tick of
    the batched engine corresponds to one second of scheduler time (the
    1 tick == 1 s == 1 heartbeat quantization, SURVEY.md §7 "Time").

    ``corrupt_prob`` has no host-side hook here: on the runtime, corrupt
    traffic is expressed through topic validators (the reference's own
    mechanism) — see tests/test_adversarial_runtime.py.

    ORDERING CONTRACT: ``hosts`` must be in engine row order — list
    position i IS peer row i of the batched half (partition components
    are ``i % components`` and outage peers hash the row id on both
    sides). Build the swarm the way topology.from_hosts expects and pass
    the same list; any other order silently picks different cut/dark
    sets than the batched run of the same plan.
    """

    def __init__(self, network, hosts, plan: FaultPlan):
        import random as _random

        self.network = network
        self.hosts = list(hosts)
        self.plan = plan
        self.rng = _random.Random(plan.seed)
        self.index = {h.peer_id: i for i, h in enumerate(self.hosts)}
        self._partitions_live: list[PartitionWindow] = []
        self._dark: dict = {}                          # widx -> set(peer ids)
        self._severed: list = []                       # [(host_a, host_b)]
        network.link_fault = self._link_fault
        sched = network.scheduler
        now = sched.now()
        for w in plan.partitions:
            sched.call_at(max(now, float(w.start)),
                          lambda w=w: self._partition_start(w))
            sched.call_at(max(now, float(w.end)),
                          lambda w=w: self._partition_end(w))
        for i, w in enumerate(plan.outages):
            sched.call_at(max(now, float(w.start)),
                          lambda i=i, w=w: self._outage_start(i, w))
            sched.call_at(max(now, float(w.end)),
                          lambda i=i: self._outage_end(i))

    # -- the one cut predicate (all transitions and the link hook agree) --

    def _is_dark(self, pid) -> bool:
        return any(pid in dark for dark in self._dark.values())

    def _is_cut(self, i: int, j: int) -> bool:
        for w in self._partitions_live:
            if i % w.components != j % w.components:
                return True
        return self._is_dark(self.hosts[i].peer_id) \
            or self._is_dark(self.hosts[j].peer_id)

    # -- link hook (Host.send) --

    def _link_fault(self, src, dst, has_data: bool = True) -> str:
        i, j = self.index.get(src), self.index.get(dst)
        if i is None or j is None:
            return "ok"
        if self._is_cut(i, j):
            return "drop"             # cut/dark link: nothing crosses
        # lossy links shed the DATA plane only (batched-half parity:
        # forward_tick masks link_ok into data_ok, control still flows),
        # so the drop draw is only spent on data-bearing frames
        if self.plan.link_drop_prob > 0.0 and has_data \
                and self.rng.random() < self.plan.link_drop_prob:
            return "drop_data"
        # duplication likewise only models retransmitted DATA frames (the
        # batched dup_offer re-offers recent deliveries on mesh edges);
        # doubling a control frame (GRAFT handled twice) would be a fault
        # class the batched half cannot mirror
        if self.plan.link_dup_prob > 0.0 and has_data \
                and self.rng.random() < self.plan.link_dup_prob:
            return "dup"
        return "ok"

    # -- window transitions --

    def _sever_cut(self) -> None:
        """Disconnect every currently-connected pair the cut predicate now
        covers (called after a window opens)."""
        for a in self.hosts:
            ia = self.index[a.peer_id]
            for pid in list(a.conns):
                ib = self.index.get(pid)
                if ib is not None and self._is_cut(ia, ib):
                    a.disconnect(pid)
                    self._severed.append((a, self.hosts[ib]))

    def _reknit(self) -> None:
        """Reconnect severed pairs no longer covered by ANY active window
        (called after a window closes); pairs another window still cuts
        stay severed until that window too ends — matching the batched
        half's per-window heal_mask & ~want_down semantics."""
        keep = []
        for a, b in self._severed:
            if self._is_cut(self.index[a.peer_id], self.index[b.peer_id]):
                keep.append((a, b))
            else:
                a.connect(b)
        self._severed = keep

    def _partition_start(self, w: PartitionWindow) -> None:
        self._partitions_live.append(w)
        self._sever_cut()

    def _partition_end(self, w: PartitionWindow) -> None:
        if w in self._partitions_live:
            self._partitions_live.remove(w)
        self._reknit()

    def _outage_start(self, widx: int, w: OutageWindow) -> None:
        dark_mask = outage_peers_host(len(self.hosts), widx, self.plan)
        self._dark[widx] = {h.peer_id
                           for h, d in zip(self.hosts, dark_mask) if d}
        self._sever_cut()

    def _outage_end(self, widx: int) -> None:
        self._dark.pop(widx, None)
        self._reknit()
