"""Live command plane: bounded host→device directive ingestion.

ROADMAP item 2's last closed-world assumption falls here: until now every
run fixed its FaultPlan and ``choose_publishers`` before the scan
started. This module is the ingress — an NDJSON directive stream
(publish / join / leave / attack-window, PAPER.md's L6 Topic/Publish
vocabulary) is validated host-side, coalesced per supervised chunk into
FIXED-SHAPE traced tensors, and injected at the PR 12 chunk boundaries
through ``trace/replay.py``'s jitted op scan — the promotion of the
replay plane from differential-testing artifact to live workload path.
Robustness-first, because an open ingress is only shippable if malformed
input, stalled producers, and overload degrade instead of wedging a
multi-host window:

- **refusal by name**: every malformed or out-of-range directive line is
  refused with a :class:`DirectiveError` naming the field (the
  ``check_hbm_budget`` discipline applied to ingress); refusals are
  journaled (``directive_refused``) and CONSUMED — the stream offset
  advances past them, so a resumed run re-refuses identically instead of
  replaying garbage.
- **admission control**: each chunk gets at most ``slots`` primitive ops
  (a jit-static shape — every frame compiles once, empty coast frames
  included). Offered load beyond the slot budget is load-shed
  deterministically by stream position, never a crash or a retrace; the
  shed count is journaled per chunk and totaled in the terminal marker.
- **coast mode**: the chunk-boundary drain waits for the stream's tick
  watermark to cover the chunk (timed directives pace the chip to the
  producer). When the producer goes silent past ``stall_timeout_s`` the
  run COASTS — the chip keeps stepping with empty (all-NOP) frames, the
  journal gets an ``ingest_stalled`` marker carrying the consumed offset
  and the producer-restart command, and each coasting boundary throttles
  by ``coast_poll_s`` so a stalled run does not sprint arbitrarily far
  from its stream. New bytes end the episode (``ingest_resumed``).
- **exactly-once resume**: frames consume a contiguous PREFIX of the
  stream (shed and refused lines included), so one byte offset is a
  complete ingestion cursor. The supervisor stamps it into every
  checkpoint sidecar (``stream_offset=`` — sim/checkpoint.py clear-line
  discipline) and seeks the queue there on resume: a SIGKILL→relaunch
  (PR 14 supervisor) replays ingestion from that exact offset, applying
  every directive exactly once and reproducing the uninterrupted
  trajectory bit for bit.
- **rank symmetry** (:class:`BroadcastCommands`): under multihost only
  rank 0 tails the stream; the drained frame — fixed-shape int32
  tensors — broadcasts to every rank before the apply, so all ranks run
  the same traced program over the same chunk inputs and the apply's
  collectives stay rank-symmetric.

Deliberately jax-free at module level (the resilience.py ethos): the
parser and queue run before and without any backend; only
:func:`apply_frame` imports jax, delegating to ``trace.replay.replay``
(whose JOIN/LEAVE branches call ``refresh_nbr_subscribed`` and whose
static-``cfg`` jit makes the per-chunk apply one trace, ever).

Directive grammar (one JSON object per line)::

    {"op": "publish", "tick": T, "peer": P, "topic": C}
    {"op": "join",    "tick": T, "peer": P, "topic": C}
    {"op": "leave",   "tick": T, "peer": P, "topic": C}
    {"op": "attack",  "tick": T, "kind": "storm", "topic": C,
     "peers": [P0, P1, ...]}        # coordinated publish storm
    {"op": "attack",  "tick": T, "kind": "eclipse",
     "peers": [P0, ...]}            # cut targets' honest<->honest edges
    {"op": "attack",  "tick": T, "kind": "censor",
     "peers": [P0, ...]}            # flip peers into censoring actors
    {"op": "compose", "tick": T, "parts": [{...}, ...]}
                                    # several tickless parts, one boundary
    {"op": "tick", "tick": T}       # watermark only: "stream covers < T"
    {"op": "end"}                   # producer finished (clean EOF)

``tick`` is optional (default: apply at the next drained boundary —
live mode, excluded from the bit-exact contract); timed directives apply
at the boundary of the chunk containing their tick. Producers should
emit non-decreasing ticks: a directive behind a later-tick line still
applies (prefix consumption), just late (journaled ``lag_ticks``).
Recorded reference traces (PAPER.md L5 schema, trace/bus.py event
shapes) feed the same queue: JOIN/LEAVE/PUBLISH_MESSAGE events map to
directives (``timestamp``→tick via ``heartbeat_interval``), other event
types are counted and skipped (``directive_skipped`` — they describe
router internals the live engine derives itself).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import NamedTuple

import numpy as np

# primitive op codes — MUST mirror trace/replay.py (asserted by
# tests/test_commands.py); duplicated so the parser/queue import no jax
OP_NOP = 0
OP_JOIN = 8
OP_LEAVE = 9
OP_PUBLISH = 10

# command-plane-only op codes (ISSUE 20): deliberately OUTSIDE replay's
# op space [0, N_OPS=14) — apply_frame masks them to NOP before the
# replay scan (lax.switch would clamp them onto DISCONNECT otherwise)
# and routes them through the jitted attack pass instead
ATTACK_OP_BASE = 16
OP_ECLIPSE = 16     # a=target peer: cut its honest<->honest edges
OP_CENSOR = 17      # a=peer: flip it into a censoring spam actor

# trace-event types that map onto live directives; everything else in
# the L5 schema is router bookkeeping the engine derives itself
_TRACE_OPS = {"JOIN": "join", "LEAVE": "leave", "PUBLISH_MESSAGE": "publish"}


class DirectiveError(ValueError):
    """A directive line was refused BY NAME (malformed JSON, unknown op,
    out-of-range peer/topic, oversized batch). Refused lines are
    journaled and consumed — never a crash, never a retrace."""


class Parsed(NamedTuple):
    """One accepted line: primitive ``(kind, peer, topic)`` ops (empty
    for watermark/end lines), the apply tick (-1 = next boundary), and
    what the line was (``directive``/``trace``/``tick``/``end``)."""

    ops: tuple
    tick: int
    kind: str


def _int_field(d: dict, name: str, lo: int, hi: int, what: str) -> int:
    v = d.get(name)
    if not isinstance(v, int) or isinstance(v, bool):
        raise DirectiveError(
            f"directive {what!r}: field {name!r} must be an integer, got "
            f"{v!r}")
    if not lo <= v < hi:
        raise DirectiveError(
            f"directive {what!r}: {name}={v} out of range [{lo}, {hi})")
    return v


def _tick_of(d: dict, what: str) -> int:
    v = d.get("tick", -1)
    if not isinstance(v, int) or isinstance(v, bool) or v < -1:
        raise DirectiveError(
            f"directive {what!r}: tick must be a non-negative integer "
            f"(or absent for apply-on-arrival), got {v!r}")
    return v


def parse_line(line, *, n_peers: int, n_topics: int,
               max_batch: int = 256, peer_index: dict | None = None,
               topic_index: dict | None = None,
               heartbeat_interval: float = 1.0) -> Parsed:
    """Parse one NDJSON line into primitive ops; raises
    :class:`DirectiveError` naming the offence on anything malformed.
    Accepts both the directive grammar and recorded trace events
    (module docstring); unsupported trace types return an empty
    ``Parsed(kind="skip:<TYPE>")`` so callers can count them."""
    if isinstance(line, bytes):
        try:
            line = line.decode()
        except UnicodeDecodeError as e:
            raise DirectiveError(f"directive line is not UTF-8: {e}") from e
    line = line.strip()
    if not line:
        return Parsed((), -1, "blank")
    try:
        d = json.loads(line)
    except json.JSONDecodeError as e:
        raise DirectiveError(
            f"directive line is not valid JSON: {e} — {line[:80]!r}") from e
    if not isinstance(d, dict):
        raise DirectiveError(
            f"directive line must be a JSON object, got "
            f"{type(d).__name__}")

    if "type" in d and "op" not in d:       # recorded trace event (L5)
        return _parse_trace_event(d, n_peers=n_peers, n_topics=n_topics,
                                  peer_index=peer_index,
                                  topic_index=topic_index,
                                  heartbeat_interval=heartbeat_interval)

    op = d.get("op")
    if op == "end":
        return Parsed((), -1, "end")
    if op == "tick":
        t = _tick_of(d, "tick")
        if t < 0:
            raise DirectiveError(
                "directive 'tick': a watermark line requires an explicit "
                "non-negative tick")
        return Parsed((), t, "tick")
    if op in ("publish", "join", "leave"):
        p = _int_field(d, "peer", 0, n_peers, op)
        c = _int_field(d, "topic", 0, n_topics, op)
        return Parsed(((op, p, c),), _tick_of(d, op), "directive")
    if op == "attack":
        ops = _attack_ops(d, n_peers=n_peers, n_topics=n_topics,
                          max_batch=max_batch)
        return Parsed(tuple(ops), _tick_of(d, "attack"), "directive")
    if op == "compose":
        ops = _compose_ops(d, n_peers=n_peers, n_topics=n_topics,
                           max_batch=max_batch)
        return Parsed(tuple(ops), _tick_of(d, "compose"), "directive")
    raise DirectiveError(
        f"directive op {op!r} unknown (supported: publish, join, leave, "
        "attack, compose, tick, end)")


_ATTACK_KINDS = ("storm", "eclipse", "censor")


def _attack_ops(d: dict, *, n_peers: int, n_topics: int,
                max_batch: int) -> list:
    """The ``attack`` directive body shared by the top-level line and
    ``compose`` parts: kind + peers → primitive ops, every malformation
    refused BY NAME."""
    kind = d.get("kind")
    if kind not in _ATTACK_KINDS:
        raise DirectiveError(
            f"directive 'attack': unknown kind {kind!r} (supported: "
            "'storm' — a coordinated publish storm from the listed "
            "peers; 'eclipse' — cut the listed targets' honest edges; "
            "'censor' — flip the listed peers into censoring spam "
            "actors; combine kinds with op 'compose')")
    if kind == "storm":
        c = _int_field(d, "topic", 0, n_topics, "attack")
    else:
        if "topic" in d:
            raise DirectiveError(
                f"directive 'attack': kind {kind!r} takes no 'topic' "
                "field (it acts on peers, not a topic)")
        c = 0
    peers = d.get("peers")
    if not isinstance(peers, list) or not peers:
        raise DirectiveError(
            "directive 'attack': field 'peers' must be a non-empty "
            "list of peer ids")
    if len(peers) > max_batch:
        raise DirectiveError(
            f"directive 'attack': batch of {len(peers)} peers exceeds "
            f"max_batch={max_batch} — split the window into smaller "
            "directives")
    prim = {"storm": "publish", "eclipse": "eclipse",
            "censor": "censor"}[kind]
    ops = []
    for p in peers:
        if not isinstance(p, int) or isinstance(p, bool) \
                or not 0 <= p < n_peers:
            raise DirectiveError(
                f"directive 'attack': peer {p!r} out of range "
                f"[0, {n_peers})")
        ops.append((prim, p, c))
    return ops


def _compose_ops(d: dict, *, n_peers: int, n_topics: int,
                 max_batch: int) -> list:
    """The ``compose`` form (ISSUE 20): one timed line carrying several
    directive parts that land at the SAME boundary — the composed attack
    scenarios ROADMAP item 2 names (eclipse+censorship on one region,
    storms against the gater's RED admission). Parts are ordinary
    directive objects WITHOUT their own tick; nesting is refused."""
    parts = d.get("parts")
    if not isinstance(parts, list) or not parts:
        raise DirectiveError(
            "directive 'compose': field 'parts' must be a non-empty "
            "list of directive objects")
    ops: list = []
    for i, part in enumerate(parts):
        if not isinstance(part, dict):
            raise DirectiveError(
                f"directive 'compose': part {i} must be a JSON object, "
                f"got {type(part).__name__}")
        if "tick" in part:
            raise DirectiveError(
                f"directive 'compose': part {i} must not carry its own "
                "tick — the compose line's tick times every part")
        pop = part.get("op")
        if pop == "compose":
            raise DirectiveError(
                "directive 'compose': parts cannot nest another compose")
        if pop in ("publish", "join", "leave"):
            p = _int_field(part, "peer", 0, n_peers, pop)
            c = _int_field(part, "topic", 0, n_topics, pop)
            ops.append((pop, p, c))
        elif pop == "attack":
            ops.extend(_attack_ops(part, n_peers=n_peers,
                                   n_topics=n_topics,
                                   max_batch=max_batch))
        else:
            raise DirectiveError(
                f"directive 'compose': part {i} op {pop!r} unknown "
                "(supported parts: publish, join, leave, attack)")
    if len(ops) > max_batch:
        raise DirectiveError(
            f"directive 'compose': {len(ops)} primitive ops exceed "
            f"max_batch={max_batch} — split the scenario into smaller "
            "compose lines")
    return ops


def _parse_trace_event(d: dict, *, n_peers: int, n_topics: int,
                       peer_index, topic_index,
                       heartbeat_interval: float) -> Parsed:
    typ = d.get("type")
    if not isinstance(typ, str):
        raise DirectiveError(
            f"trace event field 'type' must be a string, got {typ!r}")
    mapped = _TRACE_OPS.get(typ)
    if mapped is None:
        return Parsed((), -1, f"skip:{typ}")
    ts = d.get("timestamp", 0.0)
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        raise DirectiveError(
            f"trace event {typ!r}: timestamp must be a number, got {ts!r}")
    tick = max(0, int(float(ts) / max(heartbeat_interval, 1e-9)))

    def _peer(v):
        if peer_index is not None:
            if v not in peer_index:
                raise DirectiveError(
                    f"trace event {typ!r}: peer {v!r} not in peer_index")
            return int(peer_index[v])
        try:
            p = int(v)
        except (TypeError, ValueError):
            raise DirectiveError(
                f"trace event {typ!r}: peer id {v!r} is not an integer "
                "and no peer_index was provided") from None
        if not 0 <= p < n_peers:
            raise DirectiveError(
                f"trace event {typ!r}: peer {p} out of range "
                f"[0, {n_peers})")
        return p

    def _topic(v):
        if topic_index is not None:
            if v not in topic_index:
                raise DirectiveError(
                    f"trace event {typ!r}: topic {v!r} not in topic_index")
            return int(topic_index[v])
        try:
            c = int(v)
        except (TypeError, ValueError):
            raise DirectiveError(
                f"trace event {typ!r}: topic {v!r} is not an integer and "
                "no topic_index was provided") from None
        if not 0 <= c < n_topics:
            raise DirectiveError(
                f"trace event {typ!r}: topic {c} out of range "
                f"[0, {n_topics})")
        return c

    pl_key = {"JOIN": "join", "LEAVE": "leave",
              "PUBLISH_MESSAGE": "publishMessage"}[typ]
    pl = d.get(pl_key) or {}
    peer = _peer(d.get("peerID"))
    topic = _topic(pl.get("topic"))
    return Parsed(((mapped, peer, topic),), tick, "trace")


class Frame(NamedTuple):
    """One chunk's coalesced directive tensors + host-side ingest vitals.
    ``op/a/b/c`` are ``[slots]`` int32 (NOP-padded) — the fixed traced
    shape every chunk shares. ``offset`` is the consumed stream cursor
    AFTER this frame (the exactly-once stamp); ``notes`` are journal
    events accumulated since the previous frame, submitted by the
    supervisor only after the chunk that carried them confirmed."""

    op: np.ndarray
    a: np.ndarray
    b: np.ndarray
    c: np.ndarray
    count: int              # ops applied this frame
    shed: int               # ops shed this frame
    shed_total: int
    refused_total: int
    applied_total: int
    offset: int             # consumed stream byte offset after this frame
    lag: int                # worst (chunk_start - directive tick) applied
    depth: int              # queued directive lines after the drain
    coasting: bool
    notes: tuple            # ((kind, meta-dict), ...) for the journal


def empty_frame(slots: int, *, offset: int = 0, coasting: bool = False,
                notes: tuple = ()) -> Frame:
    z = np.zeros(int(slots), np.int32)
    return Frame(op=z, a=z.copy(), b=z.copy(), c=z.copy(), count=0, shed=0,
                 shed_total=0, refused_total=0, applied_total=0,
                 offset=int(offset), lag=0, depth=0, coasting=coasting,
                 notes=notes)


def apply_frame(state, cfg, tp, frame: Frame):
    """Inject a frame into the state through the jitted replay scan
    (trace/replay.py): join/leave flip ``subscribed`` and refresh the
    neighbor view, publish seeds the message ring. ``cfg`` is the jit
    key — use the BASE config (not the degrade ladder's exec config) so
    the apply compiles exactly once per run. Works unchanged on sharded
    multihost states: the ops index global peer rows and XLA keeps the
    scatter/gather rank-symmetric.

    Attack lanes (``op >= ATTACK_OP_BASE``) live OUTSIDE replay's op
    space — lax.switch would clamp them onto DISCONNECT — so they are
    masked to NOP before the replay scan and routed through a separate
    jitted attack pass. The mask + extra dispatch is priced only on
    frames that actually carry attack ops, keeping the common path at
    ONE replay trace."""
    import jax.numpy as jnp

    from ..trace.replay import replay
    op_h = np.asarray(frame.op)
    has_attack = bool((op_h >= ATTACK_OP_BASE).any())
    rep_op = np.where(op_h >= ATTACK_OP_BASE,
                      np.int32(OP_NOP), op_h) if has_attack else frame.op
    state = replay(state, cfg, tp, jnp.asarray(rep_op),
                   jnp.asarray(frame.a), jnp.asarray(frame.b),
                   jnp.asarray(frame.c))
    if has_attack:
        state = _attack_apply_fn()(state, cfg, tp, jnp.asarray(op_h),
                                   jnp.asarray(frame.a))
    return state


_attack_jit = None


def _attack_apply_fn():
    """Lazily-built jitted attack pass for OP_ECLIPSE/OP_CENSOR lanes.

    Censor flips the listed peers into spam actors (``state.malicious``
    — they answer no IWANTs and are counted by faults.attacker_mask, so
    ScoreResponse contracts see them). Eclipse cuts every honest<->
    honest edge crossing the target boundary through churn's
    take_edges_down — the same edge-symmetric construction as
    faults.edge_cut_mask, but driven by the directive's explicit peer
    list instead of a prefix fraction. Sybil edges stay up: an eclipsed
    peer keeps its attacker links, the classic eclipse topology."""
    global _attack_jit
    if _attack_jit is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        from ..ops.churn import take_edges_down
        from .invariants import FAULT_CENSOR, FAULT_ECLIPSE

        @partial(jax.jit, static_argnames=("cfg",))
        def fn(state, cfg, tp, op, a):
            n = state.neighbors.shape[0]
            lanes = jnp.clip(a, 0, n - 1)
            # scatter-max: NOP lanes carry False and cannot pollute
            tgt = jnp.zeros(n, bool).at[lanes].max(op == OP_ECLIPSE)
            coh = jnp.zeros(n, bool).at[lanes].max(op == OP_CENSOR)
            any_ecl = tgt.any()
            any_cen = coh.any()
            malicious = state.malicious | coh
            honest = ~malicious
            known = (state.neighbors >= 0) & (state.reverse_slot >= 0)
            nbr = jnp.clip(state.neighbors, 0, n - 1)
            cross = ((tgt[:, None] ^ tgt[nbr])
                     & honest[:, None] & honest[nbr] & known)
            go_down = cross & state.connected & any_ecl
            state = state._replace(malicious=malicious)
            state = take_edges_down(state, cfg, tp, go_down)
            flags = (state.fault_flags
                     | jnp.where(any_ecl, jnp.uint32(FAULT_ECLIPSE),
                                 jnp.uint32(0))
                     | jnp.where(any_cen, jnp.uint32(FAULT_CENSOR),
                                 jnp.uint32(0)))
            return state._replace(fault_flags=flags)

        _attack_jit = fn
    return _attack_jit


class _Entry(NamedTuple):
    tick: int
    ops: tuple
    offset: int             # stream offset after this line


class CommandQueue:
    """Bounded directive ingestion from an NDJSON stream (module
    docstring). A reader thread tails ``source`` from the resume offset,
    refusing malformed lines by name and enqueueing valid ones into a
    bounded deque — a full queue blocks the reader (producer
    backpressure: memory stays bounded however far the producer runs
    ahead; through a FIFO the pause reaches the producer as real pipe
    backpressure). ``frame_for`` drains a contiguous stream prefix at
    each chunk boundary into a fixed-``slots`` :class:`Frame`.

    ``chaos`` (parallel/resilience.ChaosPlan) drills the degradation
    paths: ``ingest_stall@TICK:SECS`` pauses the reader, the watchdog
    trips, the run coasts; ``ingest_kill@TICK`` stops it for good."""

    def __init__(self, source: str, *, n_peers: int, n_topics: int,
                 msg_window: int, slots: int = 64, maxlen: int = 4096,
                 stall_timeout_s: float = 10.0, coast_poll_s: float = 0.05,
                 follow: bool = True, max_batch: int = 256,
                 peer_index: dict | None = None,
                 topic_index: dict | None = None,
                 heartbeat_interval: float = 1.0, chaos=None,
                 poll_s: float = 0.02):
        if slots < 1:
            raise ValueError(f"CommandQueue: slots={slots} must be >= 1")
        self.source = source
        self.n_peers = int(n_peers)
        self.n_topics = int(n_topics)
        self.msg_window = int(msg_window)
        self.slots = int(slots)
        self.maxlen = int(maxlen)
        self.stall_timeout_s = float(stall_timeout_s)
        self.coast_poll_s = float(coast_poll_s)
        self.follow = follow
        self.max_batch = int(max_batch)
        self.peer_index = peer_index
        self.topic_index = topic_index
        self.heartbeat_interval = float(heartbeat_interval)
        self._chaos = chaos
        self._poll_s = float(poll_s)

        self._cond = threading.Condition()
        self._q: collections.deque = collections.deque()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._pause_until = 0.0     # chaos ingest_stall
        self._killed = False        # chaos ingest_kill
        self._eof = False
        self._primed = False        # reader has parsed >= 1 line
        self._watermark = -1        # highest timed tick parsed
        self._clean_offset = 0      # offset after the last parsed line
        self._consumed = 0          # offset after the last drained line
        self._last_progress = time.monotonic()
        self._coasting = False
        self._notes: list = []
        self._frames: collections.OrderedDict = collections.OrderedDict()
        self.refused_total = 0
        self.skipped_total = 0
        self.shed_total = 0
        self.applied_total = 0

    # ---- lifecycle --------------------------------------------------------

    def start(self, offset: int = 0) -> "CommandQueue":
        """Begin tailing at ``offset`` (the checkpoint's stamped
        ``stream_offset`` on resume; 0 for a fresh run)."""
        if self._thread is not None:
            return self
        self._consumed = self._clean_offset = int(offset)
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(target=self._read_loop, daemon=True,
                                        name="graft-ingest")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def consumed_offset(self) -> int:
        return self._consumed

    @property
    def stalled(self) -> bool:
        return self._coasting

    def resume_cmd(self, offset: int) -> str:
        """The producer-restart command of record (the dashboard's
        COASTING banner surfaces this verbatim): at a stall the consumed
        offset equals the producer's durable progress — the queue only
        reports a stall once it has drained every written byte."""
        return (f"python scripts/directive_producer.py --stream <input> "
                f"--out {self.source} --from-offset {offset}")

    # ---- chaos hooks (parallel/resilience.ChaosPlan) ----------------------

    def pause_reader(self, seconds: float) -> None:
        self._pause_until = time.monotonic() + float(seconds)

    def kill_reader(self) -> None:
        self._killed = True

    # ---- reader thread ----------------------------------------------------

    def _note(self, kind: str, **meta) -> None:
        with self._cond:
            self._notes.append((kind, meta))

    def _read_loop(self) -> None:
        fh = None
        pos = self._clean_offset
        try:
            while not self._stop.is_set():
                if self._killed:
                    return
                if time.monotonic() < self._pause_until:
                    time.sleep(self._poll_s)
                    continue
                if fh is None:
                    try:
                        fh = open(self.source, "rb")
                        fh.seek(pos)
                    except OSError:
                        time.sleep(self._poll_s)
                        continue
                line = fh.readline()
                if not line or not line.endswith(b"\n"):
                    # torn tail mid-append rides to the next poll; plain
                    # EOF only ends a non-follow stream
                    fh.seek(pos)
                    if not self.follow and not line:
                        with self._cond:
                            self._eof = True
                            self._cond.notify_all()
                        return
                    time.sleep(self._poll_s)
                    continue
                pos += len(line)
                self._ingest_line(line, pos)
                if self._eof:
                    return
        finally:
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass

    def _ingest_line(self, line: bytes, offset_after: int) -> None:
        try:
            parsed = parse_line(
                line, n_peers=self.n_peers, n_topics=self.n_topics,
                max_batch=self.max_batch, peer_index=self.peer_index,
                topic_index=self.topic_index,
                heartbeat_interval=self.heartbeat_interval)
        except DirectiveError as e:
            with self._cond:
                self._primed = True
                self.refused_total += 1
                self._notes.append(("directive_refused",
                                    {"reason": str(e)[:200],
                                     "offset": offset_after}))
                self._clean_offset = offset_after
                self._last_progress = time.monotonic()
                self._cond.notify_all()
            return
        with self._cond:
            self._primed = True
            self._last_progress = time.monotonic()
            if parsed.kind == "end":
                self._eof = True
                self._clean_offset = offset_after
                self._cond.notify_all()
                return
            if parsed.kind.startswith("skip:"):
                self.skipped_total += 1
                self._notes.append(("directive_skipped",
                                    {"type": parsed.kind[5:],
                                     "offset": offset_after}))
                self._clean_offset = offset_after
                self._cond.notify_all()
                return
            if parsed.tick >= 0:
                self._watermark = max(self._watermark, parsed.tick)
            if parsed.ops:
                while len(self._q) >= self.maxlen \
                        and not self._stop.is_set():
                    # producer backpressure: bounded memory — the drain
                    # frees slots and notifies
                    self._cond.wait(0.2)
                self._q.append(_Entry(parsed.tick, parsed.ops,
                                      offset_after))
            # watermark/blank lines advance the consumable offset only
            # once nothing queued precedes them (prefix discipline is
            # enforced at drain time via entry offsets)
            self._clean_offset = offset_after
            self._cond.notify_all()

    # ---- chunk-boundary drain ---------------------------------------------

    def frame_for(self, chunk_start: int, chunk_ticks: int) -> Frame:
        """The boundary drain: a contiguous stream prefix of directives
        due before ``chunk_start + chunk_ticks``, coalesced into the
        fixed-shape frame (admission-controlled, overflow shed), cached
        by ``chunk_start`` so retries and speculation re-fetch the SAME
        frame instead of draining twice."""
        cached = self._frames.get(int(chunk_start))
        if cached is not None:
            return cached
        if self._chaos is not None:
            try:
                self._chaos.fire_ingest(int(chunk_start), self)
            except Exception:
                pass        # chaos drills must never fail the run
        chunk_end = int(chunk_start) + int(chunk_ticks)
        frame = self._drain(int(chunk_start), chunk_end)
        self._frames[int(chunk_start)] = frame
        while len(self._frames) > 8:
            self._frames.popitem(last=False)
        return frame

    def _covered(self, chunk_end: int) -> bool:
        """The stream is known complete for this chunk: EOF, or the tick
        watermark proves every directive before ``chunk_end`` arrived
        (requires non-decreasing producer ticks). An UNTIMED stream —
        primed, watermark still -1 — never blocks; an unread one (the
        reader hasn't parsed a single line yet) is indistinguishable
        from a slow producer and must wait, not free-run."""
        if self._eof:
            return True
        if not self._primed:
            return False
        return self._watermark < 0 or self._watermark >= chunk_end

    def _drain(self, chunk_start: int, chunk_end: int) -> Frame:
        with self._cond:
            while not self._covered(chunk_end) and not self._stop.is_set():
                idle = time.monotonic() - self._last_progress
                if self._coasting and idle < self.stall_timeout_s:
                    # new bytes since the stall: the episode is over —
                    # resume the blocking discipline so directives due
                    # THIS chunk still land on time
                    self._coasting = False
                    self._notes.append(("ingest_resumed",
                                        {"tick": chunk_start,
                                         "offset": self._offset_now()}))
                    continue
                if self._coasting:
                    break       # still silent: keep coasting
                if idle >= self.stall_timeout_s:
                    self._coasting = True
                    off = self._offset_now()
                    self._notes.append((
                        "ingest_stalled",
                        {"tick": chunk_start, "offset": off,
                         "source": self.source,
                         "resume_cmd": self.resume_cmd(off)}))
                    break
                self._cond.wait(min(0.1, self.stall_timeout_s - idle
                                    + 0.01))
            if self._coasting and self._covered(chunk_end):
                # the stream caught up (or hit EOF) while we coasted
                self._coasting = False
                self._notes.append(("ingest_resumed",
                                    {"tick": chunk_start,
                                     "offset": self._offset_now()}))

            ops: list = []
            shed = 0
            lag = 0
            while self._q and self._q[0].tick < chunk_end:
                e = self._q.popleft()
                if e.tick >= 0:
                    lag = max(lag, chunk_start - e.tick)
                for prim in e.ops:
                    if len(ops) < self.slots:
                        ops.append(prim)
                    else:
                        shed += 1
                self._consumed = e.offset
                self._cond.notify_all()     # free backpressured reader
            if not self._q:
                # nothing queued precedes the reader head: watermark,
                # refused, and skipped lines are consumed too
                self._consumed = max(self._consumed, self._clean_offset)
            self.shed_total += shed
            self.applied_total += len(ops)
            if shed:
                self._notes.append(("ingest_shed",
                                    {"tick": chunk_start, "shed": shed,
                                     "slots": self.slots}))
            notes, self._notes = tuple(self._notes), []
            depth = len(self._q)
            coasting = self._coasting
            offset = self._consumed

        op = np.zeros(self.slots, np.int32)
        a = np.zeros(self.slots, np.int32)
        b = np.zeros(self.slots, np.int32)
        c = np.zeros(self.slots, np.int32)
        for i, (kind, peer, topic) in enumerate(ops):
            a[i] = peer
            c[i] = topic
            if kind == "publish":
                op[i] = OP_PUBLISH
                # deterministic ring slot: a pure function of (boundary,
                # frame position) — resume-safe with no extra cursor;
                # collisions recycle the oldest window entry, the
                # engine's own msg-ring semantics
                op_b = (chunk_start * self.slots + i) % self.msg_window
                b[i] = op_b
            elif kind == "eclipse":
                op[i] = OP_ECLIPSE
                b[i] = -1
            elif kind == "censor":
                op[i] = OP_CENSOR
                b[i] = -1
            else:
                op[i] = OP_JOIN if kind == "join" else OP_LEAVE
                b[i] = -1
        if coasting:
            time.sleep(self.coast_poll_s)   # coast-mode pacing
        return Frame(op=op, a=a, b=b, c=c, count=len(ops), shed=shed,
                     shed_total=self.shed_total,
                     refused_total=self.refused_total,
                     applied_total=self.applied_total, offset=int(offset),
                     lag=int(lag), depth=depth, coasting=coasting,
                     notes=notes)

    def _offset_now(self) -> int:
        # producer-restart cursor: everything durably PARSED is on disk
        # in the source file, so a producer resuming the feed appends
        # after the last complete line — distinct from ``Frame.offset``
        # (the consumer cursor checkpoints stamp), which only advances
        # as entries drain into frames
        return self._clean_offset

    # the supervisor's apply hook (one shared implementation)
    apply = staticmethod(apply_frame)


class BroadcastCommands:
    """Multihost wrapper: rank 0 owns the real :class:`CommandQueue`;
    every rank calls ``frame_for`` at the same boundary and the drained
    frame broadcasts as fixed-shape arrays
    (``multihost_utils.broadcast_one_to_all``) — identical chunk inputs
    on every rank, so the compiled apply and its collectives stay
    rank-symmetric. Frames are cached post-broadcast so a repeated
    fetch (retry paths) can never run the collective on one rank only."""

    def __init__(self, inner: CommandQueue | None, *, slots: int):
        self.inner = inner
        self.slots = int(slots)
        self._frames: collections.OrderedDict = collections.OrderedDict()
        self.applied_total = 0
        self.shed_total = 0
        self.refused_total = 0
        self.consumed_offset = 0

    def start(self, offset: int = 0) -> "BroadcastCommands":
        if self.inner is not None:
            self.inner.start(offset)
        return self

    def close(self) -> None:
        if self.inner is not None:
            self.inner.close()

    def frame_for(self, chunk_start: int, chunk_ticks: int) -> Frame:
        cached = self._frames.get(int(chunk_start))
        if cached is not None:
            return cached
        from jax.experimental import multihost_utils
        if self.inner is not None:
            f = self.inner.frame_for(chunk_start, chunk_ticks)
            payload = np.stack([f.op, f.a, f.b, f.c]).astype(np.int32)
            meta = np.array([f.count, f.shed, f.shed_total,
                             f.refused_total, f.applied_total, f.offset,
                             f.lag, f.depth, int(f.coasting)], np.int64)
            notes = f.notes
        else:
            payload = np.zeros((4, self.slots), np.int32)
            meta = np.zeros(9, np.int64)
            notes = ()
        payload, meta = multihost_utils.broadcast_one_to_all(
            (payload, meta))
        payload = np.asarray(payload)
        meta = [int(v) for v in np.asarray(meta)]
        frame = Frame(op=payload[0], a=payload[1], b=payload[2],
                      c=payload[3], count=meta[0], shed=meta[1],
                      shed_total=meta[2], refused_total=meta[3],
                      applied_total=meta[4], offset=meta[5], lag=meta[6],
                      depth=meta[7], coasting=bool(meta[8]), notes=notes)
        self.applied_total = frame.applied_total
        self.shed_total = frame.shed_total
        self.refused_total = frame.refused_total
        self.consumed_offset = frame.offset
        self._frames[int(chunk_start)] = frame
        while len(self._frames) > 8:
            self._frames.popitem(last=False)
        return frame

    @property
    def stalled(self) -> bool:
        return self.inner.stalled if self.inner is not None else False

    apply = staticmethod(apply_frame)


def write_stream(path: str, directives: list, *, end: bool = True) -> int:
    """Test/bench helper: write a directive list as an fsync'd NDJSON
    stream (+ terminal ``end`` marker); returns the byte size."""
    with open(path, "w") as f:
        for d in directives:
            f.write(json.dumps(d) + "\n")
        if end:
            f.write(json.dumps({"op": "end"}) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return os.path.getsize(path)
