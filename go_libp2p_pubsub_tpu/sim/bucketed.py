"""Degree-bucketed edge planes: per-tick cost O(ΣD), not O(N·D_max).

Heavy-tailed underlays (sim/topology.powerlaw) give a few hub peers two
orders of magnitude more edges than the median peer. The dense engine
pads EVERY peer's neighbor-slot axis to ``k_slots = D_max``, so both the
resting HBM of the K-axis planes and every per-edge op pay N·D_max even
when ΣD ≪ N·D_max — at D_max/D_mean = 16 that is a 16x tax on a graph
whose edge count never changed.

This module keeps the peers partitioned (host-side, at topology build —
:func:`sim.topology.powerlaw_buckets`) into O(log D_max) contiguous
id-ordered degree classes, hubs first. Each class's edge planes are
padded only to that class's ceiling K_b, so:

- resting bytes of a K-axis plane:  Σ_b N_b · bytes_row(K_b)  ≈ ΣD
- per-edge compute: every op runs once per bucket at [N_b, ·, K_b]

The ONLY cross-bucket traffic is the reverse-edge exchange: edge planes
concatenate into one flat ΣD-element space and each bucket gathers its
reverse values through a precomputed flat index (``BucketedState.rev``)
— every gather is sized ΣD or N_b·K_b, never N·D_max (the HLO guard in
tests/test_bucketed.py pins this).

Execution is a COLOCATED FORK of sim/engine.step, op for op and
key-split for key-split: the dense path is untouched (its HLO and RNG
stream stay byte-identical with bucketing off), and the fork reuses the
dense kernels verbatim wherever a per-bucket view suffices (publish,
scoring, selection, gater admission, take/bring edge transitions, fault
membership hashes). Under ``SimConfig.bucketed_rng = "dense"`` every
noise draw happens at the dense [N, k_slots] shape and each bucket
consumes its slice, so the bucketed trajectory is BIT-EXACT against the
dense engine on the same graph (the parity tests' contract);
``"bucket"`` folds the bucket index into the key and draws at bucket
width, making the RNG cost itself scale with ΣD (the production mode
for heavy-tailed scenarios — a different but equally valid trajectory).

Not every engine feature is bucketable; :func:`check_bucketable`
refuses the unsupported ones BY NAME rather than silently diverging.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.bits import U32, pack_bool, unpack_bool
from .config import SimConfig, TopicParams
from .state import (NEVER, _COMPACT_CODECS, _TICK16_NEVER, _TICK16_SAT,
                    SimState, _check_compact)

# SimState fields carrying the K (neighbor-slot) axis — the planes this
# module stores per bucket at that bucket's ceiling K_b. Everything else
# stays full-width on the global half (row-major peer planes slice by
# rows into bucket views; message tables and scalars replicate).
EDGE_FIELDS = (
    "neighbors", "connected", "outbound", "reverse_slot",
    "nbr_subscribed", "disconnect_tick", "direct", "mesh", "fanout",
    "backoff", "graft_tick", "mesh_active",
    "first_message_deliveries", "mesh_message_deliveries",
    "mesh_failure_penalty", "invalid_message_deliveries",
    "behaviour_penalty",
    "gater_deliver", "gater_duplicate", "gater_ignore", "gater_reject",
)

# K-free peer-major planes a bucket VIEW row-slices (and _merge concats
# back). ip_group / app_score / malicious stay GLOBAL even in views:
# compute_scores and the forward pass index them by GLOBAL neighbor id.
ROW_FIELDS = (
    "subscribed", "fanout_lastpub", "gater_validate", "gater_throttle",
    "gater_last_throttle", "have", "deliver_tick", "deliver_from",
    "iwant_pending",
)


class EdgePlanes(NamedTuple):
    """One degree class's K-axis planes at that class's width K_b."""

    neighbors: jnp.ndarray            # [Nb, Kb] i32 global peer ids
    connected: jnp.ndarray            # [Nb, Kb] bool
    outbound: jnp.ndarray             # [Nb, Kb] bool
    reverse_slot: jnp.ndarray         # [Nb, Kb] i32 slot in the neighbor
    nbr_subscribed: jnp.ndarray       # [Nb, T, Kb] bool
    disconnect_tick: jnp.ndarray      # [Nb, Kb] i32
    direct: jnp.ndarray               # [Nb, Kb] bool
    mesh: jnp.ndarray                 # [Nb, T, Kb] bool
    fanout: jnp.ndarray               # [Nb, T, Kb] bool
    backoff: jnp.ndarray              # [Nb, T, Kb] i32
    graft_tick: jnp.ndarray           # [Nb, T, Kb] i32
    mesh_active: jnp.ndarray          # [Nb, T, Kb] bool
    first_message_deliveries: jnp.ndarray    # [Nb, T, Kb] f32
    mesh_message_deliveries: jnp.ndarray     # [Nb, T, Kb] f32
    mesh_failure_penalty: jnp.ndarray        # [Nb, T, Kb] f32
    invalid_message_deliveries: jnp.ndarray  # [Nb, T, Kb] f32
    behaviour_penalty: jnp.ndarray    # [Nb, Kb] f32
    gater_deliver: jnp.ndarray        # [Nb, Kb] f32
    gater_duplicate: jnp.ndarray      # [Nb, Kb] f32
    gater_ignore: jnp.ndarray         # [Nb, Kb] f32
    gater_reject: jnp.ndarray         # [Nb, Kb] f32


class BucketedState(NamedTuple):
    """The degree-bucketed twin of :class:`SimState`.

    ``g`` is a SimState whose EDGE_FIELDS are ZERO-WIDTH placeholders
    (``v[..., :0]`` — leading N axis intact, so every op that reads
    ``state.neighbors.shape[0]`` for the peer count still sees N); the
    real edge planes live in ``e``, one :class:`EdgePlanes` per bucket.
    ``rev[b]`` is the [Nb, Kb] int32 FLAT reverse-edge index into the
    concatenated ΣD edge space (invalid slots point at themselves) —
    pure topology, computed once in :func:`bucketize_state` and carried
    so a donated scan never rebuilds it."""

    g: SimState
    e: tuple            # tuple[EdgePlanes], hubs first
    rev: tuple          # tuple[jnp.ndarray [Nb, Kb] i32]

    # the supervisor/checkpoint plumbing (sim/supervisor.py tick_ref,
    # checkpoint.fleet_axis, _write_crash_dump) reads `.tick` /
    # `.fault_flags` off whatever state an engine carries — on the
    # bucketed layout both live on the global half. Properties, not
    # fields: pytree flattening and _replace see only (g, e, rev).
    @property
    def tick(self):
        return self.g.tick

    @property
    def fault_flags(self):
        return self.g.fault_flags


def _buckets(cfg: SimConfig) -> list:
    """cfg.degree_buckets -> [(row_start, n_rows, k_ceil)] hubs first."""
    out, start = [], 0
    for n_rows, kb in cfg.degree_buckets:
        out.append((start, int(n_rows), int(kb)))
        start += int(n_rows)
    return out


def check_bucketable(cfg: SimConfig) -> None:
    """Refuse, BY NAME, every config the bucketed fork does not carry.

    The fork mirrors sim/engine.step op for op; features it does not
    mirror must fail loudly here instead of silently diverging from the
    dense trajectory."""
    if cfg.degree_buckets is None:
        raise ValueError("bucketed execution needs cfg.degree_buckets "
                         "(see sim/topology.powerlaw_buckets)")
    bks = tuple((int(r), int(k)) for r, k in cfg.degree_buckets)
    if any(r <= 0 or k <= 0 for r, k in bks):
        raise ValueError(f"degree_buckets={bks}: every (n_rows, k_ceil) "
                         "entry must be positive")
    if sum(r for r, _ in bks) != cfg.n_peers:
        raise ValueError(
            f"degree_buckets rows sum to {sum(r for r, _ in bks)} but "
            f"n_peers={cfg.n_peers}; buckets must tile the id space")
    if any(bks[i][1] < bks[i + 1][1] for i in range(len(bks) - 1)):
        raise ValueError(f"degree_buckets={bks}: k_ceil must be "
                         "non-increasing (hubs first)")
    if bks[0][1] != cfg.k_slots:
        raise ValueError(
            f"degree_buckets[0] k_ceil={bks[0][1]} != k_slots="
            f"{cfg.k_slots}: the widest bucket defines the dense width")
    if cfg.bucketed_rng not in ("dense", "bucket"):
        raise ValueError(f"bucketed_rng={cfg.bucketed_rng!r}: expected "
                         "'dense' (bit-exact vs the dense engine) or "
                         "'bucket' (ΣD-cost draws)")
    if cfg.router != "gossipsub":
        raise ValueError(f"router={cfg.router!r}: the bucketed fork "
                         "mirrors only the gossipsub step")
    if cfg.flood_publish:
        raise ValueError("flood_publish is not bucketed")
    if getattr(cfg, "record_provenance", False):
        raise ValueError("record_provenance (deliver_from attribution) "
                         "is not bucketed")
    if cfg.validation_queue_cap > 0:
        raise ValueError("validation_queue_cap > 0 (throttle charging) "
                         "is not bucketed")
    if getattr(cfg, "edge_queue_cap", 0) > 0:
        raise ValueError("edge_queue_cap > 0 is not bucketed")
    if cfg.sub_leave_prob > 0.0 or cfg.sub_join_prob > 0.0:
        raise ValueError("subscription churn (sub_leave_prob/"
                         "sub_join_prob) is not bucketed")
    if cfg.max_iwant_per_tick < cfg.msg_window:
        raise ValueError(
            f"max_iwant_per_tick={cfg.max_iwant_per_tick} < msg_window="
            f"{cfg.msg_window}: the budgeted-IWANT scan is not bucketed")
    if cfg.hop_mode in ("pallas", "pallas-mxu"):
        raise ValueError(f"hop_mode={cfg.hop_mode!r}: the fused VMEM hop "
                         "kernels are dense-only")
    if 2 * cfg.n_topics > 32:
        raise ValueError(
            f"n_topics={cfg.n_topics}: the bucketed reverse-edge "
            "exchange packs 2*n_topics mask bits into one u32 payload; "
            "2*n_topics > 32 is refused")


# ---------------------------------------------------------------------------
# bucketize / densify


def _rev_tables(cfg: SimConfig):
    bks = _buckets(cfg)
    starts = np.array([s for s, _, _ in bks], np.int32)
    kbs = np.array([kb for _, _, kb in bks], np.int32)
    bases = np.cumsum([0] + [c * kb for _, c, kb in bks])[:-1].astype(np.int64)
    return bks, starts, kbs, bases


def _flat_rev(cfg: SimConfig, e: tuple, row_offsets=None) -> tuple:
    """Per-bucket [Nb, Kb] flat reverse-edge index into the ΣD space.

    For a valid edge (row i of bucket b, slot s) with neighbor j owned by
    bucket c: ``bases[c] + (j - starts[c]) * K_c + reverse_slot``.
    Invalid slots index THEMSELVES, so an exchange returns the slot's own
    payload there — callers mask with the valid-slot predicate exactly as
    the dense edge_gather_packed does.

    ``row_offsets[b]`` declares that ``e[b]`` carries only a row WINDOW of
    bucket b starting that many rows in (the ``bucketize_state(rows=)``
    shard-build path): the self indices stay GLOBAL flat positions, so
    shard-built rev planes concatenate into exactly the full build's."""
    bks, starts, kbs, bases = _rev_tables(cfg)
    n = cfg.n_peers
    j_starts = jnp.asarray(starts)
    j_kbs = jnp.asarray(kbs)
    j_bases = jnp.asarray(bases.astype(np.int32))
    out = []
    for b, (s, c, kb) in enumerate(bks):
        nbr = e[b].neighbors
        rsl = e[b].reverse_slot
        off = 0 if row_offsets is None else int(row_offsets[b])
        rows = nbr.shape[0]
        valid = (nbr >= 0) & (rsl >= 0)
        nc = jnp.clip(nbr, 0, n - 1)
        cb = jnp.searchsorted(j_starts, nc, side="right") - 1
        flat = j_bases[cb] + (nc - j_starts[cb]) * j_kbs[cb] \
            + jnp.clip(rsl, 0, None)
        own = int(bases[b]) \
            + (off + jnp.arange(rows, dtype=jnp.int32))[:, None] * kb \
            + jnp.arange(kb, dtype=jnp.int32)[None, :]
        out.append(jnp.where(valid, flat, own).astype(jnp.int32))
    return tuple(out)


def bucketize_state(state: SimState, cfg: SimConfig,
                    rows: tuple | None = None) -> BucketedState:
    """Split a DECODED (compute-layout) dense SimState into bucket planes.

    Slots at or beyond a bucket's ceiling are DROPPED — the topology
    builder guarantees they are empty (checked here when the arrays are
    concrete; a live edge there would silently vanish otherwise).

    ``rows=(start, count)`` declares that ``state``'s peer-major planes
    carry ONLY that contiguous row window of the global id space (a
    shard build — parallel/multihost.init_bucketed_local): each bucket's
    planes cover the window's intersection with the bucket (possibly 0
    rows), and the flat reverse indices stay GLOBAL, so concatenating
    the shards' buckets row-wise reproduces the full build bit for bit
    (tests/test_bucketed.py ragged shard-build contract). The global
    dense state never needs to materialize."""
    check_bucketable(cfg)
    bks = _buckets(cfg)
    r0 = 0 if rows is None else int(rows[0])
    rc = cfg.n_peers if rows is None else int(rows[1])
    if rows is not None and not isinstance(state.neighbors, jax.core.Tracer) \
            and int(state.neighbors.shape[0]) != rc:
        raise ValueError(
            f"bucketize_state: rows={tuple(rows)} declared but the state "
            f"carries {int(state.neighbors.shape[0])} peer rows")
    e, offs = [], []
    for s, c, kb in bks:
        lo, hi = max(s, r0), min(s + c, r0 + rc)
        cnt = max(0, hi - lo)
        lo = lo if cnt else s                 # empty window: offset 0
        sl = slice(lo - r0, lo - r0 + cnt)
        if cnt and not isinstance(state.neighbors, jax.core.Tracer):
            tail = np.asarray(state.neighbors[sl, kb:])
            if tail.size and not np.all(tail < 0):
                raise ValueError(
                    f"bucketize_state: bucket rows [{lo}, {lo + cnt}) carry "
                    f"live edges beyond their k_ceil={kb} — the "
                    "degree_buckets partition does not cover this graph")
        planes = {}
        for f in EDGE_FIELDS:
            v = getattr(state, f)
            planes[f] = v[sl, ..., :kb]
        e.append(EdgePlanes(**planes))
        offs.append(lo - s)
    e = tuple(e)
    g = state._replace(**{f: getattr(state, f)[..., :0]
                          for f in EDGE_FIELDS})
    return BucketedState(g=g, e=e,
                         rev=_flat_rev(cfg, e,
                                       row_offsets=None if rows is None
                                       else offs))


_PAD_FILLS = dict(
    neighbors=-1, reverse_slot=-1,
    disconnect_tick=int(NEVER), graft_tick=int(NEVER), backoff=0,
)


def densify_state(bs: BucketedState, cfg: SimConfig) -> SimState:
    """Pad every bucket back to k_slots and concat: the dense compute-
    layout SimState (inverse of bucketize_state; pad fills are the dense
    engine's resting values at never-used slots, so a bucketize/densify
    round trip of a dense trajectory state is exact)."""
    k = cfg.k_slots
    cols = {f: [] for f in EDGE_FIELDS}
    for b, (s, c, kb) in enumerate(_buckets(cfg)):
        for f in EDGE_FIELDS:
            v = getattr(bs.e[b], f)
            pad = k - v.shape[-1]
            if pad:
                fill = _PAD_FILLS.get(f, False if v.dtype == jnp.bool_
                                      else 0)
                widths = [(0, 0)] * (v.ndim - 1) + [(0, pad)]
                v = jnp.pad(v, widths, constant_values=fill)
            cols[f].append(v)
    return bs.g._replace(
        **{f: jnp.concatenate(vs, axis=0) for f, vs in cols.items()})


# ---------------------------------------------------------------------------
# storage codecs (the bucketed twin of state.encode_state/decode_state)


def _enc(codec, v, tick):
    if codec == "bf16":
        return jax.lax.bitcast_convert_type(v.astype(jnp.bfloat16),
                                            jnp.uint16)
    if codec == "tick16":
        rel = jnp.clip(v - tick, -_TICK16_SAT, _TICK16_SAT)
        return jnp.where(v == NEVER, _TICK16_NEVER, rel).astype(jnp.int16)
    if codec == "packK":
        return pack_bool(v)
    if codec == "slot8":
        return v.astype(jnp.int8)
    return v


def _dec(codec, v, tick, kb):
    if codec == "bf16":
        return jax.lax.bitcast_convert_type(
            v, jnp.bfloat16).astype(jnp.float32)
    if codec == "tick16":
        e = v.astype(jnp.int32)
        return jnp.where(e == _TICK16_NEVER, jnp.int32(int(NEVER)),
                         tick + e)
    if codec == "packK":
        return unpack_bool(v, kb)
    if codec == "slot8":
        return v.astype(jnp.int32)
    return v


def encode_bucketed(bs: BucketedState, cfg: SimConfig) -> BucketedState:
    """STORED layout of a bucketed state: the dense codec table applied
    per plane — bucket planes pack their bools at K_b width, so the
    stored bytes scale with ΣD. The zero-width edge placeholders on
    ``g`` stay compute-typed in BOTH layouts (type-stable scan carry;
    they hold no bytes either way)."""
    if cfg.state_precision == "f32":
        return bs
    _check_compact(cfg)
    tick = bs.g.tick
    gout = {}
    for f, codec in _COMPACT_CODECS.items():
        if codec is None or f in EDGE_FIELDS:
            continue
        gout[f] = _enc(codec, getattr(bs.g, f), tick)
    e = tuple(
        ep._replace(**{f: _enc(_COMPACT_CODECS[f], getattr(ep, f), tick)
                       for f in EDGE_FIELDS
                       if _COMPACT_CODECS[f] is not None})
        for ep in bs.e)
    return BucketedState(g=bs.g._replace(**gout), e=e, rev=bs.rev)


def decode_bucketed(bs: BucketedState, cfg: SimConfig) -> BucketedState:
    """Inverse of :func:`encode_bucketed` (identity under "f32")."""
    if cfg.state_precision == "f32":
        return bs
    _check_compact(cfg)
    if bs.g.deliver_from.dtype != jnp.int8:
        raise TypeError(
            "decode_bucketed: state is already in the compute layout")
    tick = bs.g.tick
    gout = {}
    for f, codec in _COMPACT_CODECS.items():
        if codec is None or f in EDGE_FIELDS:
            continue
        gout[f] = _dec(codec, getattr(bs.g, f), tick,
                       cfg.k_slots)
    bks = _buckets(cfg)
    e = tuple(
        ep._replace(**{f: _dec(_COMPACT_CODECS[f], getattr(ep, f), tick,
                               bks[b][2])
                       for f in EDGE_FIELDS
                       if _COMPACT_CODECS[f] is not None})
        for b, ep in enumerate(bs.e))
    return BucketedState(g=bs.g._replace(**gout), e=e, rev=bs.rev)


# ---------------------------------------------------------------------------
# bucket views: the per-bucket SimState the dense kernels run on


def _view(bs: BucketedState, b: int, cfg: SimConfig) -> SimState:
    """Bucket ``b`` as a SimState: its edge planes at [Nb, ·, Kb], the
    ROW_FIELDS row-sliced to its rows, everything else global. Ops read
    the LOCAL peer count from array shapes; global-id consumers
    (compute_scores P5/P6, the fault membership hashes) take the global
    planes / explicit row_start, so a view is a faithful row window."""
    s, c, _ = _buckets(cfg)[b]
    out = {f: getattr(bs.e[b], f) for f in EDGE_FIELDS}
    for f in ROW_FIELDS:
        out[f] = jax.lax.slice_in_dim(getattr(bs.g, f), s, s + c, axis=0)
    return bs.g._replace(**out)


def _merge(bs: BucketedState, views: list) -> BucketedState:
    """Concat per-bucket views back: edge planes to ``e``, ROW_FIELDS
    rows in bucket (= id) order, scalars/message tables from the LAST
    view (every view carries identical global planes; forks that write
    them — publish, record_flags — run on ``g`` directly instead)."""
    e = tuple(EdgePlanes(**{f: getattr(v, f) for f in EDGE_FIELDS})
              for v in views)
    rows = {f: jnp.concatenate([getattr(v, f) for v in views], axis=0)
            for f in ROW_FIELDS}
    return BucketedState(g=bs.g._replace(**rows), e=e, rev=bs.rev)


# ---------------------------------------------------------------------------
# the cross-bucket primitive: flat reverse-edge exchange


def _exchange_flat(bs: BucketedState, payloads: list) -> list:
    """payloads[b] is [Nb, Kb]; returns each edge's REVERSE edge's
    payload, per bucket. One ΣD-element concat + per-bucket [Nb, Kb]
    gathers — nothing here is sized N·K_max.

    Under an active kernel mesh with the halo route, the exchange rides
    :func:`parallel.halo.route_bucketed_flat` instead: each device PUSHES
    its locally-owned flat slots to the device owning the reverse slot
    (the rev involution makes push-to-rev == gather-from-rev), so the
    cross-device traffic is capacity-padded all_to_alls, never a ΣD
    all-gather. The replicated route keeps the concat+gather below —
    under GSPMD that all-gathers ΣD elements, not N·K_max."""
    from ..parallel.kernel_context import current_kernel_mesh

    ctx = current_kernel_mesh()
    if ctx is not None and ctx.route == "halo":
        from ..parallel.halo import route_bucketed_flat
        return route_bucketed_flat(payloads, list(bs.rev))
    flat = jnp.concatenate([p.reshape(-1) for p in payloads])
    return [flat[r] for r in bs.rev]


def _split_planes(p):
    if p.ndim == 2:
        return [p]
    return [p[:, ti, :] for ti in range(p.shape[1])]


def _exchange_masks(bs: BucketedState, planes_per_bucket: list) -> list:
    """Exchange a list of bool mask planes (each [Nb, Kb] or
    [Nb, T, Kb]) across the reverse edges — the bucketed twin of
    ops/heartbeat.edge_gather_packed's single-u32-payload formulation.
    Returns, per bucket, the gathered planes in the same shapes, ANDed
    with the valid-slot predicate exactly as the dense path masks."""
    flat_lists = [[q for p in planes for q in _split_planes(p)]
                  for planes in planes_per_bucket]
    nb = len(flat_lists[0])
    if nb > 32:
        raise ValueError(f"_exchange_masks: {nb} bit planes exceed one "
                         "u32 payload")
    sh = (U32(1) << jnp.arange(nb, dtype=U32))[None, :, None]
    payloads = [jnp.sum(jnp.stack(planes, axis=1).astype(U32) * sh,
                        axis=1, dtype=U32)
                for planes in flat_lists]
    got = _exchange_flat(bs, payloads)
    out = []
    for b, gword in enumerate(got):
        ep = bs.e[b]
        valid = (ep.neighbors >= 0) & (ep.reverse_slot >= 0)
        bits = ((gword[:, None, :]
                 >> jnp.arange(nb, dtype=U32)[None, :, None])
                & U32(1)).astype(bool) & valid[:, None, :]
        res, i = [], 0
        for p in planes_per_bucket[b]:
            if p.ndim == 2:
                res.append(bits[:, i, :])
                i += 1
            else:
                t = p.shape[1]
                res.append(jnp.stack([bits[:, i + ti, :]
                                      for ti in range(t)], axis=1))
                i += t
        out.append(res)
    return out


def _gw_b(table: jnp.ndarray, nbr_b: jnp.ndarray) -> jnp.ndarray:
    """[W, N] global packed word table gathered along a bucket's
    neighbors -> [W, Kb, Nb] (the dense gather_words_rows layout, at
    bucket width). Neighbors clip to [0, N-1] exactly as the dense
    forward pass clips before its gather, so invalid slots read the
    same row-0 words there — every consumer masks them."""
    n = table.shape[1]
    return jnp.transpose(table[:, jnp.clip(nbr_b, 0, n - 1)], (0, 2, 1))


# ---------------------------------------------------------------------------
# RNG discipline


def _mk_noise(cfg: SimConfig):
    """``noise(key, b, kind)``: the uniform noise a bucket's selection /
    admission / churn draw consumes. kind is "ntk" ([·, T, K]) or "nk"
    ([·, K]).

    "dense": draw at the FULL dense shape from the dense call site's key
    and hand bucket b its row/slot slice — every bucket consumes the
    exact dense stream (bit-exact parity; XLA CSEs the per-bucket
    duplicate draws of the same key+shape). "bucket": fold the bucket
    index into the key and draw at bucket width — O(ΣD) RNG, a
    different (equally seeded) trajectory."""
    bks = _buckets(cfg)
    n, t, kmax = cfg.n_peers, cfg.n_topics, cfg.k_slots

    if cfg.bucketed_rng == "dense":
        def noise(key, b, kind):
            s, c, kb = bks[b]
            if kind == "ntk":
                return jax.random.uniform(key, (n, t, kmax))[
                    s:s + c, :, :kb]
            return jax.random.uniform(key, (n, kmax))[s:s + c, :kb]
    else:
        def noise(key, b, kind):
            s, c, kb = bks[b]
            kk = jax.random.fold_in(key, b)
            return jax.random.uniform(
                kk, (c, t, kb) if kind == "ntk" else (c, kb))
    return noise


# ---------------------------------------------------------------------------
# heartbeat fork (ops/heartbeat.heartbeat, op for op at bucket width)


def _heartbeat_b(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
                 key: jax.Array, noise):
    """Per-bucket mirror of ops/heartbeat.heartbeat. Every local decision
    runs once per bucket at [Nb, T, Kb]; the three cross-peer exchanges
    ride the flat reverse-edge involution (_exchange_masks). The
    lax.cond regime gates keep the dense predicates — ANY over ALL
    buckets — so a gated block runs for every bucket or none, exactly as
    the dense heartbeat's all-rows cond does. Returns (merged state,
    scores, scores_all, inc_gossip, fwd_send), the last four per-bucket
    lists."""
    from functools import reduce

    from ..ops.score_ops import (advance_active_latch, apply_prune_penalty,
                                 compute_scores, decayed)
    from ..ops.bits import prefix_count
    from ..ops.selection import masked_median, select_random, select_top

    bks = _buckets(cfg)
    B = len(bks)
    t = cfg.n_topics
    tick = bs.g.tick
    ks = jax.random.split(key, 8)
    smode = cfg.selection_mode

    views = [advance_active_latch(_view(bs, b, cfg), tp) for b in range(B)]
    scores_all = [compute_scores(v, cfg, tp, mask_disconnected=False,
                                 apply_decay=True) for v in views]
    scores = [jnp.where(v.connected, sa, 0.0)
              for v, sa in zip(views, scores_all)]

    sL, sb, joined, conn, out3, direct3 = [], [], [], [], [], []
    nbr_sub, backoff_ok, backoff_active = [], [], []
    mesh1, candidate, prune_neg, need = [], [], [], []
    for b, v in enumerate(views):
        _, c, kb = bks[b]
        s = scores[b][:, None, :]
        sL.append(s)
        sb.append(jnp.broadcast_to(s, (c, t, kb)))
        joined.append(v.subscribed[:, :, None])
        conn.append(v.connected[:, None, :])
        out3.append(v.outbound[:, None, :])
        direct3.append(v.direct[:, None, :])
        nbr_sub.append(v.nbr_subscribed & conn[b])
        bok = tick >= v.backoff
        backoff_ok.append(bok)
        backoff_active.append(~bok)
        mesh = v.mesh & joined[b]
        cand = conn[b] & nbr_sub[b] & ~mesh & bok & (s >= 0) \
            & ~direct3[b] & joined[b]
        pn = mesh & (s < 0)
        prune_neg.append(pn)
        mesh1.append(mesh & ~pn)
        candidate.append(cand & ~pn)
        n_mesh = jnp.sum(mesh1[b], axis=-1)
        need.append(jnp.where(n_mesh < cfg.dlo, cfg.d - n_mesh, 0))

    def _any(preds):
        return reduce(jnp.logical_or, preds)

    # 2. undersubscribed graft (dense predicate: ANY row, ALL buckets)
    pred1 = _any([jnp.any((need[b] > 0) & jnp.any(candidate[b], -1))
                  for b in range(B)])
    mesh2, graft1 = [], []
    for b in range(B):
        g1 = jax.lax.cond(
            pred1,
            lambda b=b: select_random(
                candidate[b], need[b], ks[0], max_count=cfg.d, mode=smode,
                noise=noise(ks[0], b, "ntk")),
            lambda b=b: jnp.zeros_like(candidate[b]))
        graft1.append(g1)
        mesh2.append(mesh1[b] | g1)

    # 3. oversubscribed trim
    over = [(jnp.sum(mesh2[b], axis=-1) > cfg.dhi)[..., None]
            for b in range(B)]
    pred_over = _any([jnp.any(o) for o in over])
    mesh3, prune_over = [], []
    for b in range(B):
        _, c, kb = bks[b]

        def _over_block(b=b, c=c):
            protected = select_top(sb[b], mesh2[b],
                                   jnp.full((c, t), cfg.dscore),
                                   max_count=cfg.dscore, mode=smode)
            rest = mesh2[b] & ~protected
            keep_rand = select_random(
                rest, jnp.full((c, t), cfg.d - cfg.dscore), ks[1],
                max_count=cfg.d - cfg.dscore, mode=smode,
                noise=noise(ks[1], b, "ntk"))
            kept = protected | keep_rand
            n_out_kept = jnp.sum(kept & out3[b], axis=-1)
            deficit_out = jnp.clip(cfg.dout - n_out_kept, 0)
            add_out = select_random(
                mesh2[b] & ~kept & out3[b], deficit_out, ks[2],
                max_count=cfg.dout, mode=smode,
                noise=noise(ks[2], b, "ntk"))
            remove_nonout = select_random(
                keep_rand & ~out3[b], jnp.sum(add_out, axis=-1), ks[3],
                max_count=cfg.dout, mode=smode,
                noise=noise(ks[3], b, "ntk"))
            return (kept | add_out) & ~remove_nonout

        kept = jax.lax.cond(pred_over, _over_block,
                            lambda b=b: mesh2[b])
        m3 = jnp.where(over[b], kept, mesh2[b])
        mesh3.append(m3)
        prune_over.append(mesh2[b] & ~m3)

    # 4. outbound quota top-up
    need_out, out_cand = [], []
    for b in range(B):
        n3 = jnp.sum(mesh3[b], axis=-1)
        n_out = jnp.sum(mesh3[b] & out3[b], axis=-1)
        need_out.append(jnp.where(
            (n3 >= cfg.dlo) & ~over[b][..., 0] & (n_out < cfg.dout),
            cfg.dout - n_out, 0))
        out_cand.append(candidate[b] & out3[b] & ~mesh3[b])
    pred_out = _any([jnp.any((need_out[b] > 0) & jnp.any(out_cand[b], -1))
                     for b in range(B)])
    mesh4, graft_out = [], []
    for b in range(B):
        go = jax.lax.cond(
            pred_out,
            lambda b=b: select_random(
                out_cand[b], need_out[b], ks[4], max_count=cfg.dout,
                mode=smode, noise=noise(ks[4], b, "ntk")),
            lambda b=b: jnp.zeros_like(mesh3[b]))
        graft_out.append(go)
        mesh4.append(mesh3[b] | go)

    # 5. opportunistic grafting (scalar tick gate, same for every bucket)
    og_tick = (tick % cfg.opportunistic_graft_ticks) == 0
    mesh5, og_sel = [], []
    for b in range(B):
        def _og_block(b=b):
            med = masked_median(sb[b], mesh4[b])
            og_cond = (jnp.sum(mesh4[b], -1) > 1) & \
                (med < cfg.opportunistic_graft_threshold)
            og_need = jnp.where(og_cond, cfg.opportunistic_graft_peers, 0)
            return select_random(
                candidate[b] & (sb[b] > med[..., None]) & ~mesh4[b],
                og_need, ks[5], max_count=cfg.opportunistic_graft_peers,
                mode=smode, noise=noise(ks[5], b, "ntk"))

        og = jax.lax.cond(og_tick, _og_block,
                          lambda b=b: jnp.zeros_like(mesh4[b]))
        og_sel.append(og)
        mesh5.append(mesh4[b] | og)

    grafts = [graft1[b] | graft_out[b] | og_sel[b] for b in range(B)]
    prunes = [prune_neg[b] | prune_over[b] for b in range(B)]

    # --- exchange 1: GRAFT/PRUNE receiver views ---
    ex1 = _exchange_masks(bs, [[grafts[b], prunes[b]] for b in range(B)])

    refuse, accept, inc_graft, inc_prune, bp_new = [], [], [], [], []
    for b in range(B):
        ig, ip = ex1[b]
        inc_graft.append(ig)
        inc_prune.append(ip)
        already = ig & mesh5[b]
        hard_refuse = ig & ~already & \
            (~joined[b] | backoff_active[b] | (sL[b] < 0) | direct3[b])
        cand_graft = ig & ~already & ~hard_refuse
        n_mine = jnp.sum(mesh5[b], axis=-1, keepdims=True)
        acc_out = cand_graft & out3[b]
        nonout = cand_graft & ~out3[b]
        c_out_excl = prefix_count(acc_out, exclusive=True)
        rank = prefix_count(nonout)
        acc = already | acc_out | \
            (nonout & (n_mine + c_out_excl + rank <= cfg.dhi))
        accept.append(acc)
        refuse.append(ig & ~acc)
        prune_tick = views[b].backoff - cfg.prune_backoff_ticks
        flood = backoff_active[b] & (tick < prune_tick + cfg.graft_flood_ticks)
        bp_add = jnp.sum(ig & backoff_active[b], axis=1).astype(jnp.float32) \
            + jnp.sum(ig & flood, axis=1).astype(jnp.float32)
        bp_new.append(decayed(views[b].behaviour_penalty,
                              cfg.behaviour_penalty_decay,
                              cfg.decay_to_zero) + bp_add)

    # --- exchange 2: refusal PRUNEs back to the grafting side ---
    ex2 = _exchange_masks(bs, [[refuse[b]] for b in range(B)])

    sts, new_mesh_l, new_fanout_l = [], [], []
    fanout_alive = [
        (views[b].fanout_lastpub < NEVER)
        & (tick <= views[b].fanout_lastpub + cfg.fanout_ttl_ticks)
        & ~views[b].subscribed
        for b in range(B)]
    pred_fan = _any([jnp.any(fa) for fa in fanout_alive])
    for b in range(B):
        v = views[b]
        refused_back, = ex2[b]
        nm = ((mesh5[b] | accept[b]) & ~inc_prune[b] & ~refused_back) \
            & joined[b]
        pruned_any = prunes[b] | inc_prune[b] | refused_back \
            | (refuse[b] & joined[b])
        new_backoff = jnp.where(pruned_any, tick + cfg.prune_backoff_ticks,
                                v.backoff)
        newly = nm & ~v.mesh
        removed = v.mesh & ~nm
        fa3 = fanout_alive[b][..., None]

        def _fanout_block(b=b, fa3=fa3):
            v = views[b]
            keep_f = v.fanout & conn[b] & nbr_sub[b] & \
                (sL[b] >= cfg.publish_threshold) & fa3
            need_f = jnp.where(fanout_alive[b],
                               jnp.maximum(cfg.d - jnp.sum(keep_f, -1), 0),
                               0)
            add_f = select_random(
                conn[b] & nbr_sub[b] & ~keep_f & ~direct3[b]
                & (sL[b] >= cfg.publish_threshold) & fa3,
                need_f, ks[7], max_count=cfg.d, mode=smode,
                noise=noise(ks[7], b, "ntk"))
            return keep_f | add_f

        nf = jax.lax.cond(pred_fan, _fanout_block,
                          lambda b=b: jnp.zeros_like(views[b].fanout))
        fanout_lastpub = jnp.where(fanout_alive[b], v.fanout_lastpub, NEVER)
        st = v._replace(mesh=nm, backoff=new_backoff,
                        behaviour_penalty=bp_new[b], fanout=nf,
                        fanout_lastpub=fanout_lastpub)
        st = apply_prune_penalty(st, removed, tp,
                                 decay_to_zero=cfg.decay_to_zero,
                                 apply_decay=True)
        st = st._replace(
            graft_tick=jnp.where(newly, tick, st.graft_tick),
            mesh_active=jnp.where(newly, False, st.mesh_active))
        sts.append(st)
        new_mesh_l.append(nm)
        new_fanout_l.append(nf)

    gossip_sel, send = [], []
    for b in range(B):
        _, c, kb = bks[b]
        gossip_cand = conn[b] & nbr_sub[b] & ~new_mesh_l[b] \
            & ~new_fanout_l[b] & ~direct3[b] \
            & (sL[b] >= cfg.gossip_threshold) \
            & (joined[b] | fanout_alive[b][..., None])
        n_cand = jnp.sum(gossip_cand, axis=-1)
        target = jnp.maximum(cfg.dlazy, jnp.floor(
            jnp.float32(cfg.gossip_factor) * n_cand.astype(jnp.float32)
        ).astype(jnp.int32))
        # the static bound derives from the BUCKET width: n_cand <= Kb, so
        # target <= max(Dlazy, floor(f32(factor) * f32(Kb))) in the same
        # f32 arithmetic as the dense bound derivation — never below the
        # traced target, and mode divergence is bit-identical
        # (ops/selection._select_by_keys: all formulations agree)
        gossip_bound = max(cfg.dlazy, int(np.floor(
            np.float32(cfg.gossip_factor) * np.float32(kb))))
        gossip_sel.append(select_random(
            gossip_cand, target, ks[6], max_count=gossip_bound, mode=smode,
            noise=noise(ks[6], b, "ntk")))
        send.append(new_mesh_l[b]
                    | (new_fanout_l[b] & ~views[b].subscribed[:, :, None]))

    # --- exchange 3: emitGossip + eager-forward receiver views ---
    ex3 = _exchange_masks(
        bs, [[gossip_sel[b], send[b]] for b in range(B)])
    inc_gossip = [ex3[b][0] for b in range(B)]
    fwd_send = [ex3[b][1] for b in range(B)]

    return (_merge(bs, sts), scores, scores_all, inc_gossip, fwd_send)


# ---------------------------------------------------------------------------
# forward fork (ops/propagate.forward_tick, op for op at bucket width)


def _forward_b(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
               inc_gossip_l: list, scores_l: list, key: jax.Array,
               fwd_send_l: list, noise,
               link_ok_l=None, dup_edges_l=None, censor_bits=None):
    """Per-bucket mirror of ops/propagate.forward_tick (the non-fused XLA
    formulation; check_bucketable refuses the Pallas hop modes and every
    cap/flood/provenance branch, so those paths are statically dead here).

    Message-window tables stay GLOBAL [W, N] packed words — they are
    peer-count sized, not degree sized. Only the [W, K, N] edge event
    planes split per bucket: each gather/expand/count runs at [W, Kb, Nb],
    so the hop cost is Σ_b W·Kb·Nb = W·ΣD instead of W·K_max·N. The hop
    loop carries the global frontier/have/deliver words plus per-bucket
    count tuples; per-bucket new-arrival words concatenate back along the
    peer axis each hop (buckets are contiguous id ranges)."""
    from ..ops import gater
    from ..ops.bits import (exclusive_prefix_or, n_words, pack_words,
                            popcount_sum, reduce_or, unpack_words)
    from ..ops.propagate import _bits_to_slot, _edge_topic_bits, _slot_bitplanes
    from ..ops.score_ops import decayed

    g = bs.g
    t = cfg.n_topics
    m = cfg.msg_window
    w = n_words(m)
    bks = _buckets(cfg)
    B = len(bks)
    k_fwd, k_gate = jax.random.split(key)
    del k_fwd     # gossipsub with pre-gathered fwd_send never consumes it
    mal = g.malicious
    views = [_view(bs, b, cfg) for b in range(B)]
    nbrs = [bs.e[b].neighbors for b in range(B)]

    # --- per-tick packed masks (global: message-window sized) ---
    age_pub = g.tick - g.msg_publish_tick
    alive = (age_pub >= 0) & (age_pub < cfg.history_length)
    t_m = jnp.clip(g.msg_topic, 0, t - 1)
    live_topic = (g.msg_topic >= 0) & alive
    topic_bits = pack_bool((t_m[None, :] == jnp.arange(t)[:, None])
                           & live_topic[None, :])
    alive_bits = pack_bool(alive[None, :])[0]
    invalid_bits = pack_bool((g.msg_invalid & alive)[None, :])[0]
    ignored_bits = pack_bool((g.msg_ignored & alive)[None, :])[0]
    valid_msg_bits = alive_bits & ~invalid_bits & ~ignored_bits
    vm = jnp.where(mal[None, :], alive_bits[:, None],
                   valid_msg_bits[:, None])                          # [W,N]
    inv_n = jnp.where(mal[None, :], U32(0), invalid_bits[:, None])
    ign_n = jnp.where(mal[None, :], U32(0), ignored_bits[:, None])

    have_bits = g.have.T                                             # [W,N]
    dlv_bits = pack_words(g.deliver_tick < NEVER)
    dlv_start = dlv_bits
    n_have_start = popcount_sum(have_bits, axis=(0, 1))

    data_ok_l = []
    for b, (s, c, kb) in enumerate(bks):
        if cfg.scoring_enabled:
            accept_ok = scores_l[b] >= cfg.graylist_threshold
        else:
            accept_ok = jnp.ones((c, kb), bool)
        if cfg.gater_enabled:
            d = accept_ok & (gater.accept_data(
                views[b], cfg, k_gate, noise=noise(k_gate, b, "nk"))
                | mal[s:s + c, None])
        else:
            d = accept_ok
        if link_ok_l is not None:
            d = d & link_ok_l[b]
        data_ok_l.append(d)

    if cfg.count_dtype not in ("uint8", "int32"):
        raise ValueError(
            f"count_dtype={cfg.count_dtype!r}: only 'uint8' and 'int32' "
            "are supported (numpy shorthands like 'u8' parse as OTHER "
            "widths and would silently defeat the knob)")
    cdt = jnp.dtype(cfg.count_dtype)
    if m > jnp.iinfo(cdt).max:
        raise ValueError(
            f"msg_window={m} > {jnp.iinfo(cdt).max} would wrap the "
            f"{cfg.count_dtype} hop-count accumulators; shrink the window "
            "or widen count_dtype")

    def topic_counts(events_wkn):
        return jnp.stack([
            popcount_sum(events_wkn & topic_bits[ti][:, None, None],
                         axis=0, dtype=cdt)
            for ti in range(t)]).astype(cdt)

    # -- step 1: resolve pending IWANTs from last tick --
    answer_bits = jnp.where(mal[None, :], U32(0), dlv_bits)
    if censor_bits is not None:
        answer_bits = answer_bits & ~censor_bits
    got_any_l, got_valid_any_l = [], []
    seed_nv, seed_ni, seed_ig = [], [], []
    for b, (s, c, kb) in enumerate(bks):
        sl = slice(s, s + c)
        asked_k = _slot_bitplanes(views[b].iwant_pending, kb) \
            & alive_bits[:, None, None]
        answers_k = _gw_b(answer_bits, nbrs[b])                  # [W,Kb,Nb]
        adm_kn = jnp.where(data_ok_l[b].T[None, :, :],
                           U32(0xFFFFFFFF), U32(0))
        hb_c = have_bits[:, sl]
        got_k = asked_k & answers_k & ~hb_c[:, None, :] & adm_kn
        broken_k = asked_k & ~answers_k
        if link_ok_l is not None:
            link_kn = jnp.where(link_ok_l[b].T[None, :, :],
                                U32(0xFFFFFFFF), U32(0))
            broken_k = asked_k & ~(answers_k & link_kn)
        views[b] = views[b]._replace(
            behaviour_penalty=views[b].behaviour_penalty
            + popcount_sum(broken_k, axis=0).T)
        got_any_l.append(reduce_or(got_k, axis=1))
        got_valid = got_k & vm[:, None, sl]
        got_valid_any_l.append(reduce_or(got_valid, axis=1))
        seed_nv.append(topic_counts(got_valid))
        seed_ni.append(topic_counts(got_k & inv_n[:, None, sl]))
        if cfg.gater_enabled:
            seed_ig.append(popcount_sum(got_k & ign_n[:, None, sl],
                                        axis=0, dtype=cdt).astype(cdt))
    got_any = jnp.concatenate(got_any_l, axis=1)                     # [W,N]
    got_valid_any = jnp.concatenate(got_valid_any_l, axis=1)
    have_bits = have_bits | got_any
    dlv_bits = dlv_bits | got_valid_any
    validated = popcount_sum(got_any, axis=0,
                             dtype=jnp.int32).astype(jnp.float32)    # [N]

    # -- step 2: eager forwarding, prop_substeps hops --
    allowed_l = [_edge_topic_bits(fwd_send_l[b] & data_ok_l[b][:, None, :],
                                  topic_bits, w) for b in range(B)]
    mesh_eb_l = [_edge_topic_bits(views[b].mesh, topic_bits, w)
                 for b in range(B)]
    if dup_edges_l is not None:
        age_d = g.tick - g.deliver_tick
        dup_window = pack_words((age_d >= 0)
                                & (age_d < cfg.history_gossip)) \
            & alive_bits[:, None]
        if censor_bits is not None:
            dup_window = dup_window & ~censor_bits
        dup_offer_l = [
            _gw_b(dup_window, nbrs[b]) & mesh_eb_l[b]
            & jnp.where((dup_edges_l[b] & data_ok_l[b]).T[None, :, :],
                        U32(0xFFFFFFFF), U32(0))
            for b in range(B)]
    else:
        dup_offer_l = None

    age_dlv = g.tick - g.deliver_tick
    window_old = pack_words(
        (age_dlv >= 0)
        & (age_dlv <= cfg.mesh_message_deliveries_window_ticks))

    frontier = pack_words(g.deliver_tick == g.tick) | got_valid_any
    carry0 = {
        "i": jnp.int32(0),
        "frontier": frontier,
        "have": have_bits,
        "dlv": dlv_bits,
        "dlv_new": got_valid_any,
        "nv": tuple(seed_nv),
        "ni": tuple(seed_ni),
        "dup": tuple(jnp.zeros((t, kb, c), cdt) for (s, c, kb) in bks),
        "validated": validated,
    }
    if cfg.gater_enabled:
        carry0["ig"] = tuple(seed_ig)
        carry0["gdup"] = tuple(jnp.zeros((kb, c), cdt)
                               for (s, c, kb) in bks)

    def hop(cr):
        i = cr["i"]
        frontier, have_w, dlv_new = cr["frontier"], cr["have"], cr["dlv_new"]
        validated = cr["validated"]
        is_first = i == 0
        src = frontier if censor_bits is None else frontier & ~censor_bits
        new_any_l, new_valid_l = [], []
        nv_o, ni_o, dup_o = list(cr["nv"]), list(cr["ni"]), list(cr["dup"])
        if cfg.gater_enabled:
            ig_o, gdup_o = list(cr["ig"]), list(cr["gdup"])
        for b, (s, c, kb) in enumerate(bks):
            sl = slice(s, s + c)
            offered = _gw_b(src, nbrs[b]) & allowed_l[b]
            if dup_offer_l is not None:
                offered = offered | jnp.where(is_first, dup_offer_l[b],
                                              U32(0))
            excl = exclusive_prefix_or(offered, axis=1)
            hb_c = have_w[:, sl]
            new_from_k = offered & ~excl & ~hb_c[:, None, :]
            new_any = (excl[:, -1] | offered[:, -1]) & ~hb_c         # [W,Nb]
            new_valid = new_any & vm[:, sl]
            nv_ev = new_from_k & vm[:, None, sl]
            nv_o[b] = nv_o[b] + topic_counts(nv_ev)
            ni_o[b] = ni_o[b] + topic_counts(new_from_k
                                             & inv_n[:, None, sl])
            elig = (window_old[:, sl] | dlv_new[:, sl] | new_valid) \
                & valid_msg_bits[:, None]
            dup_o[b] = dup_o[b] + topic_counts(offered & mesh_eb_l[b]
                                               & elig[:, None, :])
            if cfg.gater_enabled:
                ig_o[b] = ig_o[b] + popcount_sum(
                    new_from_k & ign_n[:, None, sl], axis=0,
                    dtype=cdt).astype(cdt)
                gdup_o[b] = gdup_o[b] + popcount_sum(
                    offered & ~new_from_k & (hb_c | new_any)[:, None, :],
                    axis=0, dtype=cdt).astype(cdt)
            new_any_l.append(new_any)
            new_valid_l.append(new_valid)
        new_any = jnp.concatenate(new_any_l, axis=1)                 # [W,N]
        new_valid = jnp.concatenate(new_valid_l, axis=1)
        if cfg.gater_enabled:
            # column-independent popcount: per-bucket pieces concat into
            # exactly the dense per-receiver sum
            validated = validated + jnp.concatenate(
                [popcount_sum(a, axis=0) for a in new_any_l], axis=0)
        out = dict(cr)
        out.update(i=i + 1, frontier=new_valid, have=have_w | new_any,
                   dlv=cr["dlv"] | new_valid, dlv_new=dlv_new | new_valid,
                   nv=tuple(nv_o), ni=tuple(ni_o), dup=tuple(dup_o),
                   validated=validated)
        if cfg.gater_enabled:
            out["ig"], out["gdup"] = tuple(ig_o), tuple(gdup_o)
        return out

    carry = jax.lax.while_loop(
        lambda cr: (cr["i"] < cfg.prop_substeps)
        & jnp.any(cr["frontier"] != 0),
        hop, carry0)
    have_bits, dlv_bits = carry["have"], carry["dlv"]
    validated = carry["validated"]

    def t2(x):
        return x[None, :, None]
    z = cfg.decay_to_zero
    caps = tp.first_message_deliveries_cap[None, :, None], \
        tp.mesh_message_deliveries_cap[None, :, None]
    for b in range(B):
        v = views[b]
        fmd_add = jnp.transpose(carry["nv"][b],
                                (2, 0, 1)).astype(jnp.float32)
        imd_add = jnp.transpose(carry["ni"][b],
                                (2, 0, 1)).astype(jnp.float32)
        mmd_add = jnp.transpose(carry["dup"][b],
                                (2, 0, 1)).astype(jnp.float32)
        v = v._replace(
            first_message_deliveries=jnp.minimum(
                decayed(v.first_message_deliveries,
                        t2(tp.first_message_deliveries_decay), z)
                + fmd_add, caps[0]),
            mesh_message_deliveries=jnp.minimum(
                decayed(v.mesh_message_deliveries,
                        t2(tp.mesh_message_deliveries_decay), z)
                + mmd_add, caps[1]),
            invalid_message_deliveries=decayed(
                v.invalid_message_deliveries,
                t2(tp.invalid_message_deliveries_decay), z) + imd_add)
        if cfg.gater_enabled:
            # throttle stays untouched: the validation cap is refused, so
            # the dense throttle add is +0 and last_throttle's where() is
            # the identity — skipping both is bit-identical
            def sum_t(x):
                return jnp.sum(x.astype(jnp.float32), axis=0).T
            v = v._replace(
                gater_deliver=v.gater_deliver + sum_t(carry["nv"][b]),
                gater_duplicate=v.gater_duplicate
                + carry["gdup"][b].astype(jnp.float32).T,
                gater_ignore=v.gater_ignore
                + carry["ig"][b].astype(jnp.float32).T,
                gater_reject=v.gater_reject + sum_t(carry["ni"][b]))
        views[b] = v

    newly_dlv = dlv_bits & ~dlv_start
    new_dlv_mask = unpack_words(newly_dlv, m)
    deliver_tick = jnp.where(new_dlv_mask, g.tick, g.deliver_tick)
    delivered = popcount_sum(have_bits, axis=(0, 1)) - n_have_start

    # -- step 3: IHAVE/IWANT for next tick (uses the UPDATED deliveries) --
    age = g.tick - deliver_tick
    window_bits = pack_words((age >= 0) & (age < cfg.history_gossip)) \
        & alive_bits[:, None]
    window_bits = jnp.where(mal[None, :], alive_bits[:, None], window_bits)
    pend_l = []
    for b, (s, c, kb) in enumerate(bks):
        if cfg.scoring_enabled:
            gossip_ok = scores_l[b] >= cfg.gossip_threshold
        else:
            gossip_ok = jnp.ones((c, kb), bool)
        valid_slots = ((nbrs[b] >= 0)
                       & (bs.e[b].reverse_slot >= 0))[:, None, :]
        inc_g = inc_gossip_l[b] & valid_slots & gossip_ok[:, None, :]
        offer = _gw_b(window_bits, nbrs[b]) \
            & _edge_topic_bits(inc_g, topic_bits, w)
        # max_iwant_per_tick >= msg_window is a check_bucketable
        # precondition, so the budgeted scan is statically dead
        excl = exclusive_prefix_or(offer, axis=1)
        chosen_k = offer & ~excl & ~have_bits[:, None, s:s + c]
        pend_l.append(_bits_to_slot(chosen_k, m))
    iwant_pending = jnp.concatenate(pend_l, axis=0)

    out = _merge(bs, views)
    g2 = out.g._replace(
        have=have_bits.T, deliver_tick=deliver_tick,
        delivered_total=out.g.delivered_total + delivered,
        iwant_pending=iwant_pending)
    if cfg.gater_enabled:
        g2 = g2._replace(gater_validate=g2.gater_validate + validated)
    return out._replace(g=g2)


# ---------------------------------------------------------------------------
# churn fork (ops/churn.churn_edges, symmetric draws over the flat exchange)


def _churn_b(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
             key: jax.Array, scores_all_l: list, noise,
             forbid_up_l=None) -> BucketedState:
    """Per-bucket mirror of ops/churn.churn_edges. The lower-GLOBAL-id
    endpoint's down/up/direct bits decide each edge (the dense rule uses
    row ids, which ARE global ids here since buckets are contiguous id
    ranges); the three decision planes ride one packed exchange.
    take_edges_down / bring_edges_up run verbatim on the views."""
    from ..ops.churn import bring_edges_up, take_edges_down

    bks = _buckets(cfg)
    B = len(bks)
    tick = bs.g.tick
    kd, ku = jax.random.split(key)
    views = [_view(bs, b, cfg) for b in range(B)]

    d_down_l, d_up_l = [], []
    for b, (s, c, kb) in enumerate(bks):
        v = views[b]
        d_down_l.append(noise(kd, b, "nk") < cfg.churn_disconnect_prob)
        if cfg.px_enabled:
            down_age = tick - v.disconnect_tick
            px_score = jnp.where(down_age > cfg.retain_score_ticks,
                                 0.0, scores_all_l[b])
            p_up = jnp.where(px_score >= cfg.accept_px_threshold,
                             cfg.churn_reconnect_prob,
                             cfg.churn_reconnect_prob
                             * cfg.px_low_score_factor)
        else:
            p_up = cfg.churn_reconnect_prob
        d_up_l.append(noise(ku, b, "nk") < p_up)

    ex = _exchange_masks(
        bs, [[d_down_l[b], d_up_l[b], views[b].direct] for b in range(B)])

    sts = []
    redial = (tick % cfg.direct_connect_ticks) == 0
    for b, (s, c, kb) in enumerate(bks):
        v = views[b]
        nbr = v.neighbors
        gd, gu, gdir = ex[b]
        mine_wins = (s + jnp.arange(c))[:, None] < nbr
        d_down = jnp.where(mine_wins, d_down_l[b], gd)
        d_up = jnp.where(mine_wins, d_up_l[b], gu)
        direct_low = jnp.where(mine_wins, v.direct, gdir)
        known = nbr >= 0
        down = known & ~v.connected
        live = known & v.connected
        go_down = live & d_down
        come_up = (down & d_up) | (down & direct_low & redial)
        if forbid_up_l is not None:
            come_up = come_up & ~forbid_up_l[b]
        v = take_edges_down(v, cfg, tp, go_down)
        v = bring_edges_up(v, cfg, come_up)
        sts.append(v)
    return _merge(bs, sts)


# ---------------------------------------------------------------------------
# fault fork (sim/faults.apply_faults, per-bucket cut masks + draws)


class BucketedFaultTick(NamedTuple):
    """Per-bucket twin of sim/faults.FaultTick: the edge-plane members are
    tuples (one [Nb, Kb] plane per bucket); corrupt/injected stay global."""

    want_down: tuple
    link_ok: tuple | None
    dup_edges: tuple | None
    corrupt: jnp.ndarray | None
    injected: jnp.ndarray


def _apply_faults_b(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
                    key: jax.Array, noise
                    ) -> tuple[BucketedState, BucketedFaultTick]:
    """Per-bucket mirror of sim/faults.apply_faults. Cut masks come from
    edge_cut_mask's row-window hooks (global-id membership predicates, so
    per-bucket masks concat into the dense mask); schedule-fact injected
    bits are identical in every bucket (OR == the dense word) and
    data-dependent bits OR across buckets (any() over slices == global
    any())."""
    from ..ops.churn import bring_edges_up, take_edges_down
    from .faults import _family_salt, _slow_edge_hash_jax, _thr32, \
        edge_cut_mask
    from .invariants import (FAULT_CENSOR, FAULT_LINK_DROP, FAULT_LINK_DUP,
                             FAULT_SLOWLINK, FAULT_STORM)

    plan = cfg.fault_plan
    bks = _buckets(cfg)
    B = len(bks)
    n = cfg.n_peers
    tick = bs.g.tick
    mal = bs.g.malicious
    if plan.slowlinks:
        kd, kdup, kc, kslow = jax.random.split(key, 4)
    else:
        kd, kdup, kc = jax.random.split(key, 3)
        kslow = None

    want_down_l = []
    inj = U32(0)
    if plan.partitions or plan.outages or plan.eclipses or plan.waves:
        sts = []
        for b, (s, c, kb) in enumerate(bks):
            v = _view(bs, b, cfg)
            wd, heal, inj_b = edge_cut_mask(
                plan, tick, v.neighbors, v.reverse_slot,
                disconnect_tick=v.disconnect_tick, malicious=mal,
                row_start=s, n_global=n)
            v = take_edges_down(v, cfg, tp, v.connected & wd)
            come_up = heal & ~v.connected & ~wd
            v = bring_edges_up(v, cfg, come_up)
            want_down_l.append(wd)
            inj = inj | inj_b
            sts.append(v)
        bs = _merge(bs, sts)
    else:
        for b, (s, c, kb) in enumerate(bks):
            wd, _, inj_b = edge_cut_mask(
                plan, tick, bs.e[b].neighbors, bs.e[b].reverse_slot,
                malicious=mal, row_start=s, n_global=n)
            want_down_l.append(wd)
            inj = inj | inj_b

    for w in plan.storms:
        inj = inj | jnp.where((tick >= w.start) & (tick < w.end),
                              U32(FAULT_STORM), U32(0))
    for w in plan.censorships:
        inj = inj | jnp.where((tick >= w.start) & (tick < w.end),
                              U32(FAULT_CENSOR), U32(0))

    conn_l = [bs.e[b].connected for b in range(B)]
    link_ok_l = dup_edges_l = corrupt = None
    if plan.link_drop_prob > 0.0:
        link_ok_l = [noise(kd, b, "nk") >= plan.link_drop_prob
                     for b in range(B)]
        drop_any = jnp.zeros((), bool)
        for b in range(B):
            drop_any = drop_any | jnp.any(~link_ok_l[b] & conn_l[b])
        inj = inj | jnp.where(drop_any, U32(FAULT_LINK_DROP), U32(0))
    if plan.slowlinks:
        kss = jax.random.split(kslow, len(plan.slowlinks))
        lk_l = [jnp.ones_like(conn_l[b]) for b in range(B)]
        stalled = jnp.zeros((), bool)
        for ci, cl in enumerate(plan.slowlinks):
            salt = _family_salt(plan.seed, "slowlink", ci)
            for b, (s, c, kb) in enumerate(bks):
                nbr_b = bs.e[b].neighbors
                h = _slow_edge_hash_jax(nbr_b, salt, row_start=s,
                                        n_global=n)
                member = (h < U32(_thr32(cl.fraction))) & (nbr_b >= 0)
                phase = (h % U32(cl.period)).astype(jnp.int32)
                open_now = ((tick + phase) % cl.period) == 0
                ok = open_now
                if cl.drop > 0.0:
                    ok = ok & (noise(kss[ci], b, "nk") >= cl.drop)
                lk_l[b] = lk_l[b] & (~member | ok)
                stalled = stalled | jnp.any(member & ~open_now & conn_l[b])
        link_ok_l = lk_l if link_ok_l is None \
            else [a & o for a, o in zip(link_ok_l, lk_l)]
        inj = inj | jnp.where(stalled, U32(FAULT_SLOWLINK), U32(0))
    if plan.link_dup_prob > 0.0:
        dup_edges_l = [(noise(kdup, b, "nk") < plan.link_dup_prob)
                       & conn_l[b] for b in range(B)]
        dup_any = jnp.zeros((), bool)
        for b in range(B):
            dup_any = dup_any | jnp.any(dup_edges_l[b])
        inj = inj | jnp.where(dup_any, U32(FAULT_LINK_DUP), U32(0))
    if plan.corrupt_prob > 0.0:
        # a [P]-sized global draw, identical to the dense site
        corrupt = jax.random.uniform(
            kc, (cfg.publishers_per_tick,)) < plan.corrupt_prob
    return bs, BucketedFaultTick(want_down=tuple(want_down_l),
                                 link_ok=None if link_ok_l is None
                                 else tuple(link_ok_l),
                                 dup_edges=None if dup_edges_l is None
                                 else tuple(dup_edges_l),
                                 corrupt=corrupt, injected=inj)


# ---------------------------------------------------------------------------
# gater decay + invariant sentinel forks


def _gater_decay_b(bs: BucketedState, cfg: SimConfig) -> BucketedState:
    """ops/gater.gater_decay split across the layout: the global
    validate/throttle planes decay on ``g``, the four per-source planes
    decay per bucket."""
    z = cfg.decay_to_zero

    def dec(v, factor):
        v = v * factor
        return jnp.where(v < z, 0.0, v)

    g = bs.g._replace(
        gater_validate=dec(bs.g.gater_validate, cfg.gater_global_decay),
        gater_throttle=dec(bs.g.gater_throttle, cfg.gater_global_decay))
    e = tuple(ep._replace(
        gater_deliver=dec(ep.gater_deliver, cfg.gater_source_decay),
        gater_duplicate=dec(ep.gater_duplicate, cfg.gater_source_decay),
        gater_ignore=dec(ep.gater_ignore, cfg.gater_source_decay),
        gater_reject=dec(ep.gater_reject, cfg.gater_source_decay))
        for ep in bs.e)
    return bs._replace(g=g, e=e)


def _record_flags_b(bs: BucketedState, cfg: SimConfig,
                    injected=None) -> BucketedState:
    """sim/invariants.record_flags over the buckets: every check is an
    any() reduction, so the OR of per-bucket words is exactly the dense
    word (global planes are rechecked per bucket — an OR-idempotent
    repeat, not a double count)."""
    from .invariants import VIOLATION_MASK, violation_flags

    if cfg.invariant_mode not in ("record", "raise"):
        raise ValueError(
            f"invariant_mode={cfg.invariant_mode!r}: expected 'off', "
            "'record', or 'raise'")
    flags = U32(0)
    for b in range(len(bs.e)):
        flags = flags | violation_flags(_view(bs, b, cfg), cfg,
                                        n_global=cfg.n_peers)
    if injected is not None:
        flags = flags | injected
    if cfg.invariant_mode == "raise":
        from jax.experimental import checkify
        viol = flags & U32(VIOLATION_MASK)
        checkify.check(viol == 0,
                       "invariant violation: fault_flags={flags}",
                       flags=viol)
    return bs._replace(g=bs.g._replace(
        fault_flags=bs.g.fault_flags | flags))


# ---------------------------------------------------------------------------
# the bucketed tick + run wrappers


def bucketed_step(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
                  key: jax.Array) -> BucketedState:
    """One tick on the degree-bucketed layout — sim/engine.step with every
    edge-plane op at bucket width. Key-split order, op order, and every
    RNG consumption site mirror engine.step exactly; under
    ``bucketed_rng="dense"`` the whole tick is bit-exact against a dense
    step on the same graph (tests/test_bucketed.py)."""
    from ..parallel.kernel_context import (current_kernel_mesh,
                                           drain_halo_overflow, peer_shards)
    from .engine import choose_publishers
    from ..ops.propagate import publish

    ctx = current_kernel_mesh()
    if ctx is not None:
        n_dev = peer_shards()
        for b, (n_rows, kb) in enumerate(cfg.degree_buckets or ()):
            if int(n_rows) % n_dev:
                raise ValueError(
                    f"bucketed_step under the sharded kernel mesh: bucket "
                    f"{b} ({int(n_rows)} rows x k_ceil {int(kb)}) does not "
                    f"tile the {n_dev}-device mesh — realign the partition "
                    "with topology.align_degree_buckets and drive the step "
                    "through parallel/sharding.make_sharded_bucketed_run")
    check_bucketable(cfg)
    noise = _mk_noise(cfg)
    bs = decode_bucketed(bs, cfg)
    if cfg.fault_plan is not None:
        key, k_fault = jax.random.split(key)
        bs, fault = _apply_faults_b(bs, cfg, tp, k_fault, noise)
    else:
        fault = None
    k_pub, k_hb, k_fwd, k_churn, k_ign, k_sub = jax.random.split(key, 6)
    del k_sub      # subscription churn is a check_bucketable refusal
    peers, topics = choose_publishers(bs.g, cfg, k_pub)
    if fault is not None and fault.corrupt is not None:
        from .invariants import FAULT_CORRUPT
        corrupt_eff = fault.corrupt & ~bs.g.malicious[peers]
        fault = fault._replace(
            corrupt=corrupt_eff,
            injected=fault.injected | jnp.where(
                jnp.any(corrupt_eff), U32(FAULT_CORRUPT), U32(0)))
    bs = bs._replace(g=publish(
        bs.g, cfg, peers, topics, k_ign,
        corrupt=fault.corrupt if fault is not None else None))
    if cfg.fault_plan is not None:
        from .faults import censor_word_mask
        censor_bits = censor_word_mask(bs.g, cfg)
    else:
        censor_bits = None
    if cfg.gater_enabled:
        bs = _gater_decay_b(bs, cfg)
    bs, scores, scores_all, inc_gossip, fwd_send = _heartbeat_b(
        bs, cfg, tp, k_hb, noise)
    bs = _forward_b(bs, cfg, tp, inc_gossip, scores, k_fwd, fwd_send,
                    noise,
                    link_ok_l=fault.link_ok if fault is not None else None,
                    dup_edges_l=fault.dup_edges
                    if fault is not None else None,
                    censor_bits=censor_bits)
    if cfg.churn_disconnect_prob > 0.0:
        bs = _churn_b(bs, cfg, tp, k_churn, scores_all, noise,
                      forbid_up_l=fault.want_down
                      if fault is not None else None)
    notes = drain_halo_overflow()
    if notes:
        bs = bs._replace(g=bs.g._replace(
            halo_overflow=bs.g.halo_overflow + sum(notes)))
    if cfg.invariant_mode != "off":
        bs = _record_flags_b(bs, cfg,
                             injected=fault.injected
                             if fault is not None else None)
    bs = bs._replace(g=bs.g._replace(tick=bs.g.tick + 1))
    return encode_bucketed(bs, cfg)


def _bucketed_run_impl(bs: BucketedState, cfg: SimConfig, tp: TopicParams,
                       key: jax.Array, n_ticks: int) -> BucketedState:
    """sim/engine._run_impl on the bucketed layout: both key schedules,
    same per-tick key sequences, one scanned tick program."""
    if cfg.key_schedule == "fold_in":
        def body(carry, _):
            k = jax.random.fold_in(key, carry.g.tick)
            return bucketed_step(carry, cfg, tp, k), None

        bs, _ = jax.lax.scan(body, bs, None, length=n_ticks)
        return bs
    if cfg.key_schedule != "host":
        raise ValueError(f"unknown key_schedule {cfg.key_schedule!r}; "
                         "expected 'host' or 'fold_in'")

    def body(carry, k):
        return bucketed_step(carry, cfg, tp, k), None

    bs, _ = jax.lax.scan(body, bs, jax.random.split(key, n_ticks))
    return bs


bucketed_run = jax.jit(_bucketed_run_impl,
                       static_argnames=("cfg", "n_ticks"))


def init_bucketed_state(cfg: SimConfig, topo, **kwargs) -> BucketedState:
    """state.init_state -> bucketize: the stored-layout BucketedState a
    bucketed run starts from. Accepts init_state's keyword planes
    (subscribed/ip_group/app_score/malicious) unchanged."""
    from .state import decode_state, init_state

    check_bucketable(cfg)
    dense = decode_state(init_state(cfg, topo, **kwargs), cfg)
    return encode_bucketed(bucketize_state(dense, cfg), cfg)
