"""The five BASELINE.json benchmark scenarios as reproducible constructors.

Each builder returns ``(cfg, tp, state)`` ready for ``engine.run``:

1. ``single_topic_1k``   — 1k-peer single-topic gossipsub, default score
   params (the gossipsub_test.go harness scale/semantics).
2. ``beacon_10k``        — 10k peers, Ethereum beacon-chain-style topic set
   (global topics everyone joins + attestation subnets joined by random
   committees) with the published beacon scoring shape: capped positive
   topic scores, heavy invalid/behaviour penalties.
3. ``churn_50k``         — 50k peers, multi-topic, connection churn each tick
   exercising backoff + retention + mesh self-healing (pubsub.go:711-757
   dead-peer path, score.go:611-644 RetainScore).
4. ``sybil_100k``        — 100k-peer mesh with 20% sybil attackers
   (the gossipsub_spam_test.go adversary roles: invalid publishes, IHAVE
   floods, unanswered IWANTs) under full scoring + colocation penalties.
5. ``router_sweep_100k`` — same 100k network built for each router variant
   (floodsub / randomsub / gossipsub) for the propagation-latency sweep.

Beyond the five BASELINE configs, two FAULT scenarios (sim/faults.py
plans attached to the config, PR 4):

6. ``partition_50k``  — 50k peers, a scheduled 2-way partition with
   RemovePeer-semantics cut + heal (delivery must recover >= 0.99).
7. ``outage_10k``     — 10k peers + churn/PX; 20% of peers go dark for a
   window and return through the churn/backoff/retention path.

Beyond those, the FRONTIER family (ISSUE 8): ``frontier_250k`` /
``frontier_500k`` / ``frontier_1m`` — the million-peer trajectory slot.
Sparse random underlay (vectorized builder), K=32, small topic set, and
the packed-by-construction sharded configuration; ``frontier_spec``
exposes the host-side inputs so multi-process runs build only their own
peer rows (parallel/multihost.py).

Seeds are fixed (314159, the reference's test seed —
validation_builtin_test.go:25-27) so every scenario is deterministic.
"""

from __future__ import annotations

import numpy as np

from ..core.params import TopicScoreParams
from .config import SimConfig, TopicParams
from .state import SimState, init_state
from . import topology

SEED = 314159


def default_topic_params(n_topics: int = 1) -> TopicParams:
    """The reference tests' canonical params shape (score_test.go style):
    all P components active with mild weights."""
    return TopicParams.from_topic_params([TopicScoreParams(
        topic_weight=1.0, time_in_mesh_weight=0.01, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=3600.0, first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.5, first_message_deliveries_cap=100.0,
        mesh_message_deliveries_weight=-1.0, mesh_message_deliveries_decay=0.5,
        mesh_message_deliveries_cap=100.0, mesh_message_deliveries_threshold=2.0,
        mesh_message_deliveries_window=0.01, mesh_message_deliveries_activation=5.0,
        mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.5,
        invalid_message_deliveries_weight=-10.0, invalid_message_deliveries_decay=0.9,
    )] * n_topics)


def single_topic_1k(n_peers: int = 1024, k_slots: int = 32, degree: int = 12,
                    ) -> tuple[SimConfig, TopicParams, SimState]:
    """Config 1: the gossipsub_test.go harness at 1k scale."""
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=1, msg_window=64,
        publishers_per_tick=8, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, default_topic_params(1), init_state(cfg, topo)


# Beacon-chain-style topic roles: (name, joined-by-all, weight, invalid_w)
# modeled on the published Eth2 gossip scoring shape — one heavy global block
# topic, a global aggregate topic, and per-subnet attestation topics joined by
# rotating committees. (Shape only; exact production constants are chain-
# config dependent.)
_BEACON_TOPICS = [
    ("beacon_block", True, 0.5),
    ("beacon_aggregate_and_proof", True, 0.5),
    ("voluntary_exit", True, 0.05),
    ("proposer_slashing", True, 0.05),
    ("attester_slashing", True, 0.05),
    ("beacon_attestation_0", False, 0.25),
    ("beacon_attestation_1", False, 0.25),
    ("beacon_attestation_2", False, 0.25),
    ("beacon_attestation_3", False, 0.25),
]


def beacon_10k(n_peers: int = 10_000, k_slots: int = 48, degree: int = 16,
               subnet_fraction: float = 0.15,
               ) -> tuple[SimConfig, TopicParams, SimState]:
    """Config 2: 10k peers over a beacon-style topic set with peer scoring."""
    rng = np.random.default_rng(SEED)
    t = len(_BEACON_TOPICS)
    subscribed = np.zeros((n_peers, t), dtype=bool)
    for i, (_, global_topic, _) in enumerate(_BEACON_TOPICS):
        if global_topic:
            subscribed[:, i] = True
        else:
            subscribed[:, i] = rng.random(n_peers) < subnet_fraction
    tp = TopicParams.from_topic_params([TopicScoreParams(
        topic_weight=w, time_in_mesh_weight=0.03, time_in_mesh_quantum=1.0,
        time_in_mesh_cap=300.0, first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.99, first_message_deliveries_cap=50.0,
        mesh_message_deliveries_weight=-1.0, mesh_message_deliveries_decay=0.97,
        mesh_message_deliveries_cap=100.0, mesh_message_deliveries_threshold=4.0,
        mesh_message_deliveries_window=0.01, mesh_message_deliveries_activation=10.0,
        mesh_failure_penalty_weight=-1.0, mesh_failure_penalty_decay=0.95,
        invalid_message_deliveries_weight=-100.0, invalid_message_deliveries_decay=0.99,
    ) for (_, _, w) in _BEACON_TOPICS])
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=t, msg_window=64,
        publishers_per_tick=16, prop_substeps=8,
        scoring_enabled=True, topic_score_cap=100.0,
        behaviour_penalty_weight=-15.9, behaviour_penalty_threshold=6.0,
        behaviour_penalty_decay=0.986, gossip_threshold=-4000.0,
        publish_threshold=-8000.0, graylist_threshold=-16000.0)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, tp, init_state(cfg, topo, subscribed=subscribed)


def churn_50k(n_peers: int = 50_000, k_slots: int = 32, degree: int = 12,
              n_topics: int = 4, disconnect_prob: float = 0.02,
              reconnect_prob: float = 0.2,
              ) -> tuple[SimConfig, TopicParams, SimState]:
    """Config 3: 50k peers, multi-topic, per-tick connection churn."""
    rng = np.random.default_rng(SEED)
    subscribed = rng.random((n_peers, n_topics)) < 0.5
    subscribed[~subscribed.any(axis=1), 0] = True
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=n_topics, msg_window=64,
        publishers_per_tick=16, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0,
        retain_score_ticks=30, churn_disconnect_prob=disconnect_prob,
        churn_reconnect_prob=reconnect_prob,
        # BASELINE config #3 names "peer_gater + backoff churn": RED
        # admission on validation overload (peer_gater.go) + PX-seeded
        # reconnects (gossipsub.go:893-973)
        gater_enabled=True, validation_queue_cap=64,
        px_enabled=True, accept_px_threshold=-50.0)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, default_topic_params(n_topics), \
        init_state(cfg, topo, subscribed=subscribed)


def sybil_100k(n_peers: int = 100_000, k_slots: int = 32, degree: int = 12,
               sybil_fraction: float = 0.2, n_sybil_ips: int = 64,
               ) -> tuple[SimConfig, TopicParams, SimState]:
    """Config 4: 100k-peer mesh, 20% sybil attackers sharing few IPs.

    Sybils publish invalid messages, advertise the whole window, and never
    answer IWANTs (the gossipsub_spam_test.go actor set); scoring must
    graylist them (P4 + P7 + P6 colocation)."""
    rng = np.random.default_rng(SEED)
    malicious = rng.random(n_peers) < sybil_fraction
    # honest peers get unique ip groups; sybils share n_sybil_ips groups
    ip_group = np.arange(n_peers, dtype=np.int32)
    ip_group[malicious] = n_peers + (rng.integers(
        0, n_sybil_ips, malicious.sum())).astype(np.int32)
    # compact group ids
    _, ip_group = np.unique(ip_group, return_inverse=True)
    ip_group = ip_group.astype(np.int32)
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=1, msg_window=32,
        publishers_per_tick=8, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=2.0, behaviour_penalty_decay=0.99,
        ip_colocation_factor_weight=-50.0, ip_colocation_factor_threshold=4,
        n_ip_groups=int(ip_group.max()) + 1,
        gossip_threshold=-10.0, publish_threshold=-50.0,
        graylist_threshold=-100.0,
        # churn + PX: honest peers reconnect preferentially to peers they
        # score above the PX threshold, so the honest mesh heals while
        # graylisted sybil edges decay (gossipsub.go:893-973); long score
        # retention keeps sybil history alive across their down-time
        churn_disconnect_prob=0.01, churn_reconnect_prob=0.2,
        px_enabled=True, accept_px_threshold=-5.0, retain_score_ticks=600)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, default_topic_params(1), \
        init_state(cfg, topo, malicious=malicious, ip_group=ip_group)


def router_sweep_100k(router: str, n_peers: int = 100_000, k_slots: int = 32,
                      degree: int = 12,
                      ) -> tuple[SimConfig, TopicParams, SimState]:
    """Config 5: one 100k network per router variant, scoring off (floodsub
    and randomsub have no scoring; comparison isolates propagation)."""
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=1, msg_window=32,
        publishers_per_tick=4, prop_substeps=8,
        router=router, scoring_enabled=False)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, TopicParams.disabled(1), init_state(cfg, topo)


def partition_50k(n_peers: int = 50_000, k_slots: int = 32, degree: int = 12,
                  n_topics: int = 2, start: int = 10, heal: int = 25,
                  components: int = 2,
                  ) -> tuple[SimConfig, TopicParams, SimState]:
    """Fault scenario 6: 50k peers, full scoring, a 2-way network partition
    on ticks [start, heal) — cross-component edges go down with RemovePeer
    semantics and redial at ``heal`` (sim/faults.py). Within the window
    each component's mesh self-heals internally; after the heal, delivery
    recovers cross-component first through gossip IHAVE/IWANT over the
    redialed (non-mesh) edges, then the heartbeat re-balances the mesh —
    the gossipsub.go self-healing contract under the harshest single
    fault. The acceptance check: delivery_fraction >= 0.99 within a
    bounded tick budget after ``heal`` (tests/test_faults.py, batched AND
    host runtime on the same plan shape)."""
    from .faults import FaultPlan, PartitionWindow
    rng = np.random.default_rng(SEED)
    subscribed = rng.random((n_peers, n_topics)) < 0.7
    subscribed[~subscribed.any(axis=1), 0] = True
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=n_topics, msg_window=64,
        publishers_per_tick=16, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0,
        retain_score_ticks=30,
        fault_plan=FaultPlan(partitions=(
            PartitionWindow(start, heal, components=components),)))
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, default_topic_params(n_topics), \
        init_state(cfg, topo, subscribed=subscribed)


def outage_10k(n_peers: int = 10_000, k_slots: int = 32, degree: int = 12,
               fraction: float = 0.2, start: int = 10, heal: int = 25,
               ) -> tuple[SimConfig, TopicParams, SimState]:
    """Fault scenario 7: 10k peers with background churn + PX; a regional
    outage takes ``fraction`` of the peers completely dark for ticks
    [start, heal), then they return through the existing churn/backoff/
    retention path (sim/faults.py outage semantics + ops/churn
    bring_edges_up). Survivor meshes must re-knit around the dark region
    (heartbeat under-subscription grafting) and re-admit the returners
    without whitewashing their score history (retain_score_ticks covers
    the window)."""
    from .faults import FaultPlan, OutageWindow
    cfg = SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=1, msg_window=64,
        publishers_per_tick=8, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0,
        churn_disconnect_prob=0.005, churn_reconnect_prob=0.2,
        px_enabled=True, accept_px_threshold=-50.0, retain_score_ticks=30,
        fault_plan=FaultPlan(outages=(
            OutageWindow(start, heal, fraction=fraction),)))
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    return cfg, default_topic_params(1), init_state(cfg, topo)


# --- frontier family: the million-peer trajectory slot (ROADMAP item 1) --
# Sparse random underlay (the vectorized builder — topology.sparse at 1M
# is O(N²) Python), K=32, a small topic set, full scoring, and the
# packed-by-construction sharded configuration: edge_gather_mode="sort" +
# sharded_route="halo", so a peer-sharded run exchanges capacity-padded
# bit-packed buckets over one all_to_all instead of dense [N,K] payload
# all-gathers (tests/test_hlo_sharded_budget.py pins the budget).
# Peer counts are powers of two: 8-way-mesh divisible and 128-lane
# friendly at every shard size.

FRONTIER_NS = {"frontier_250k": 262_144, "frontier_500k": 524_288,
               "frontier_1m": 1_048_576,
               # XL tier: compact storage precision by construction — the
               # f32 layout prices over any sane per-shard budget at these
               # N (sim/state.state_nbytes, PERF_MODEL.md frontier table)
               "frontier_4m": 4_194_304, "frontier_10m": 10_485_760}


def frontier_cfg(n_peers: int, k_slots: int = 32, n_topics: int = 2,
                 msg_window: int = 64,
                 state_precision: str = "f32") -> SimConfig:
    """The frontier SimConfig alone — no topology build. Memory accounting
    (``state_nbytes``) needs only these shapes, so budget checks price the
    REAL scenario config without minutes of 1M underlay construction
    (tests/test_multihost.py's HBM-budget acceptance test)."""
    return SimConfig(
        n_peers=n_peers, k_slots=k_slots, n_topics=n_topics,
        msg_window=msg_window, publishers_per_tick=16, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0,
        edge_gather_mode="sort", sharded_route="halo",
        state_precision=state_precision)


def frontier_spec(n_peers: int, k_slots: int = 32, degree: int = 8,
                  n_topics: int = 2, msg_window: int = 64,
                  subnet_fraction: float = 0.3,
                  state_precision: str = "f32",
                  rows: tuple[int, int] | None = None,
                  ) -> tuple[SimConfig, TopicParams, "topology.Topology",
                             np.ndarray]:
    """The frontier scenario WITHOUT device state: ``(cfg, tp, topo,
    subscribed)`` — the host-side inputs a multi-process run feeds to
    ``parallel.multihost.init_state_local`` so each process builds only
    its own ``[N/P, ...]`` rows (a 1M-peer state never materializes on
    one host). Single-process callers use :func:`frontier`, which
    composes this with ``init_state``.

    ``rows=(start, count)`` switches to the SHARDED construction path:
    ``topology.sparse_hash`` materializes only those rows of the seeded
    circulant underlay (10M peers never build a global [N, K] table on
    any host — feed the result to ``init_state_local(...,
    topo_local=True)``). The ``subscribed`` table stays global either
    way: at [N, T] bool it is ~20 MB at 10M, and every process needs it
    to compute its neighbors' subscription view."""
    cfg = frontier_cfg(n_peers, k_slots=k_slots, n_topics=n_topics,
                       msg_window=msg_window,
                       state_precision=state_precision)
    rng = np.random.default_rng(SEED)
    subscribed = np.zeros((n_peers, n_topics), dtype=bool)
    subscribed[:, 0] = True                      # one global topic
    for t in range(1, n_topics):                 # random subnets
        subscribed[:, t] = rng.random(n_peers) < subnet_fraction
    if rows is None:
        topo = topology.sparse_fast(n_peers, k_slots, degree=degree,
                                    seed=SEED)
    else:
        topo = topology.sparse_hash(n_peers, k_slots, degree=degree,
                                    seed=SEED, rows=rows)
    return cfg, default_topic_params(n_topics), topo, subscribed


def frontier(n_peers: int, **kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Single-process frontier constructor (bench lines, reduced-N CPU
    contract runs); the state is the full ``init_state`` build."""
    cfg, tp, topo, subscribed = frontier_spec(n_peers, **kw)
    return cfg, tp, init_state(cfg, topo, subscribed=subscribed)


def frontier_250k(n_peers: int = FRONTIER_NS["frontier_250k"], **kw):
    return frontier(n_peers, **kw)


def frontier_500k(n_peers: int = FRONTIER_NS["frontier_500k"], **kw):
    return frontier(n_peers, **kw)


def frontier_1m(n_peers: int = FRONTIER_NS["frontier_1m"], **kw):
    return frontier(n_peers, **kw)


def frontier_4m(n_peers: int = FRONTIER_NS["frontier_4m"], **kw):
    """XL frontier: compact storage precision by default — the f32 layout
    at 4M peers prices ~1.8 GiB/shard on 8 devices for state alone, and
    10M would not fit a 16 GiB chip with transients (PERF_MODEL.md
    frontier-memory table). Callers can still force f32 explicitly."""
    kw.setdefault("state_precision", "compact")
    return frontier(n_peers, **kw)


def frontier_10m(n_peers: int = FRONTIER_NS["frontier_10m"], **kw):
    """The 10M-peer frontier: compact storage precision and the sharded
    construction path are the POINT of this scenario (ROADMAP item 4) —
    full-table builds take O(N·K) host RAM, so multi-process launches
    should pair it with the sharded topology builder
    (``topology.sparse_hash(..., rows=...)`` via scripts/run_multihost.py
    ``--topology sharded``)."""
    kw.setdefault("state_precision", "compact")
    return frontier(n_peers, **kw)


# --- adversary & workload library (sim/adversary.py, ISSUE 10) -----------
# Five attack/workload families with machine-checkable behavior contracts
# (delivery floor, recovery ceiling, score response). The registry entries
# below return the plain (cfg, tp, state) triple; the contracts travel on
# adversary.ATTACKS[name]() for the contract-enforcing planes
# (tests/test_adversary.py tier-1, sweep contract columns, dashboard).
# Lazy imports: adversary imports THIS module for the shared helpers.


def eclipse_small(**kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Sybil mesh takeover of a target region (adversary.eclipse)."""
    from . import adversary
    return tuple(adversary.eclipse(**kw)[:3])


def censor_small(**kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Score-gamed starvation of a victim publisher
    (adversary.censorship)."""
    from . import adversary
    return tuple(adversary.censorship(**kw)[:3])


def flashcrowd_small(**kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Hot-topic publish storm, skewed publishers
    (adversary.flash_crowd)."""
    from . import adversary
    return tuple(adversary.flash_crowd(**kw)[:3])


def slowlink_small(**kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Heterogeneous per-edge delay/drop classes (adversary.slow_link)."""
    from . import adversary
    return tuple(adversary.slow_link(**kw)[:3])


def diurnal_small(**kw) -> tuple[SimConfig, TopicParams, SimState]:
    """Scheduled diurnal join/leave waves (adversary.diurnal)."""
    from . import adversary
    return tuple(adversary.diurnal(**kw)[:3])


def eclipse_50k(n_peers: int = 50_000, k_slots: int = 32, degree: int = 12,
                **kw) -> tuple[SimConfig, TopicParams, SimState]:
    """The eclipse family at bench scale: 50k peers, windows sized for
    short measured windows (the faults_degraded bench-line discipline —
    the attack must FIRE inside a 10-tick measurement)."""
    from . import adversary
    kw.setdefault("start", 3)
    kw.setdefault("end", 8)
    return tuple(adversary.eclipse(n_peers=n_peers, k_slots=k_slots,
                                   degree=degree, **kw)[:3])


def flashcrowd_50k(n_peers: int = 50_000, k_slots: int = 32,
                   degree: int = 12, **kw
                   ) -> tuple[SimConfig, TopicParams, SimState]:
    """The flash-crowd family at bench scale (hot set scaled with N)."""
    from . import adversary
    kw.setdefault("start", 3)
    kw.setdefault("end", 8)
    kw.setdefault("hot", 64)
    return tuple(adversary.flash_crowd(n_peers=n_peers, k_slots=k_slots,
                                       degree=degree, **kw)[:3])


# --- small-N attack family (scripts/sweep_scores.py grid cells) ----------
# The same adversarial shapes as their big siblings, sized so a
# weight-variant × seed fleet of them batches into one vmapped scan on any
# backend (sim/fleet.py): the peer-score sweep's unit of work.


def sybil_small(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
                **kw) -> tuple[SimConfig, TopicParams, SimState]:
    """sybil_100k's 20%-sybil colocation attack at sweep scale."""
    return sybil_100k(n_peers=n_peers, k_slots=k_slots, degree=degree, **kw)


def partition_small(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
                    start: int = 8, heal: int = 20, **kw
                    ) -> tuple[SimConfig, TopicParams, SimState]:
    """partition_50k's 2-way cut-and-heal at sweep scale (earlier window
    so a ~40-tick sweep run has a settled post-heal recovery period)."""
    return partition_50k(n_peers=n_peers, k_slots=k_slots, degree=degree,
                         start=start, heal=heal, **kw)


def outage_small(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
                 start: int = 8, heal: int = 20, **kw
                 ) -> tuple[SimConfig, TopicParams, SimState]:
    """outage_10k's 20%-dark regional outage at sweep scale."""
    return outage_10k(n_peers=n_peers, k_slots=k_slots, degree=degree,
                      start=start, heal=heal, **kw)


# --- heavy-tailed underlay family (sim/bucketed.py, ISSUE 15) -----------
# Truncated power-law degree sequences realized by the shard-constructible
# topology.powerlaw builder, run on the degree-bucketed edge layout so
# per-tick cost and resting HBM scale with sum-of-degrees instead of
# N * D_max. These builders return (cfg, tp, BucketedState) — the state
# is for sim.bucketed.bucketed_run, NOT engine.run, so they live in
# BUCKETED_SCENARIOS rather than SCENARIOS (whose generic consumers feed
# engine.run).

POWERLAW_NS = {"powerlaw_100k": 131_072, "powerlaw_1m": 1_048_576,
               "powerlaw_10m": 10_485_760}

# Row alignment for MULTI-HOST bucketed runs: every bucket boundary rounds
# to a multiple of this, so any device/process count dividing it shards
# every bucket evenly. Deliberately INDEPENDENT of the live process count:
# the partition feeds the checkpoint fingerprint and the elastic P -> P'
# resume (sim/supervisor.py) must see the SAME partition at both sizes.
POWERLAW_MH_ALIGN = 64


def powerlaw_cfg(n_peers: int, d_min: int = 8, d_max: int = 64,
                 alpha: float = 2.0, n_topics: int = 2,
                 msg_window: int = 64, state_precision: str = "compact",
                 bucketed_rng: str = "bucket",
                 shard_align: int | None = None) -> SimConfig:
    """The heavy-tail SimConfig alone — no topology build. The bucket
    partition is closed-form (topology.powerlaw_buckets), so HBM budget
    gates price the REAL bucketed layout before any underlay
    construction (the frontier_cfg discipline). ``shard_align`` rounds
    the partition for the row-sharded multi-host plane
    (topology.align_degree_buckets; pass POWERLAW_MH_ALIGN)."""
    buckets = topology.powerlaw_buckets(n_peers, d_min=d_min, d_max=d_max,
                                        alpha=alpha)
    if shard_align is not None:
        buckets = topology.align_degree_buckets(buckets, shard_align)
    return SimConfig(
        n_peers=n_peers, k_slots=buckets[0][1], n_topics=n_topics,
        msg_window=msg_window, publishers_per_tick=16, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_decay=0.999, gossip_threshold=-100.0,
        publish_threshold=-200.0, graylist_threshold=-300.0,
        churn_disconnect_prob=0.002, churn_reconnect_prob=0.2,
        retain_score_ticks=30, state_precision=state_precision,
        degree_buckets=buckets, bucketed_rng=bucketed_rng)


def powerlaw_spec(n_peers: int, d_min: int = 8, d_max: int = 64,
                  alpha: float = 2.0, subnet_fraction: float = 0.3,
                  rows: tuple[int, int] | None = None, **cfg_kw,
                  ) -> tuple[SimConfig, TopicParams, "topology.Topology",
                             np.ndarray]:
    """The heavy-tail scenario WITHOUT device state: ``(cfg, tp, topo,
    subscribed)``. ``rows=(start, count)`` builds only that shard of the
    underlay (topology.powerlaw is a pure function of row id — concat
    across shards equals the full build bit for bit)."""
    cfg = powerlaw_cfg(n_peers, d_min=d_min, d_max=d_max, alpha=alpha,
                       **cfg_kw)
    rng = np.random.default_rng(SEED)
    subscribed = np.zeros((n_peers, cfg.n_topics), dtype=bool)
    subscribed[:, 0] = True
    for t in range(1, cfg.n_topics):
        subscribed[:, t] = rng.random(n_peers) < subnet_fraction
    topo = topology.powerlaw(n_peers, cfg.k_slots, d_min=d_min,
                             d_max=d_max, alpha=alpha, seed=SEED, rows=rows)
    return cfg, default_topic_params(cfg.n_topics), topo, subscribed


def powerlaw_mh_spec(n_peers: int, d_min: int = 8, d_max: int = 64,
                     alpha: float = 2.0, subnet_fraction: float = 0.3,
                     **cfg_kw):
    """Multi-host heavy-tail spec: ``(cfg, tp, topo_rows, subscribed)``
    where ``topo_rows(start, count)`` builds only those underlay rows
    (topology.powerlaw is a pure function of row id, so
    parallel/multihost.init_bucketed_local can call it once per local
    bucket block and the full graph never materializes on any host). The
    partition is shard-aligned by default (POWERLAW_MH_ALIGN) so the
    config fingerprints identically at every process count — the elastic
    P -> P' resume contract."""
    cfg_kw.setdefault("shard_align", POWERLAW_MH_ALIGN)
    cfg = powerlaw_cfg(n_peers, d_min=d_min, d_max=d_max, alpha=alpha,
                       **cfg_kw)
    rng = np.random.default_rng(SEED)
    subscribed = np.zeros((n_peers, cfg.n_topics), dtype=bool)
    subscribed[:, 0] = True
    for t in range(1, cfg.n_topics):
        subscribed[:, t] = rng.random(n_peers) < subnet_fraction

    def topo_rows(start: int, count: int) -> "topology.Topology":
        return topology.powerlaw(n_peers, cfg.k_slots, d_min=d_min,
                                 d_max=d_max, alpha=alpha, seed=SEED,
                                 rows=(start, count))

    return cfg, default_topic_params(cfg.n_topics), topo_rows, subscribed


def powerlaw_bucketed(n_peers: int, **kw):
    """Single-process heavy-tail constructor: (cfg, tp, BucketedState)."""
    from . import bucketed
    cfg, tp, topo, subscribed = powerlaw_spec(n_peers, **kw)
    return cfg, tp, bucketed.init_bucketed_state(cfg, topo,
                                                 subscribed=subscribed)


def powerlaw_100k(n_peers: int = POWERLAW_NS["powerlaw_100k"], **kw):
    return powerlaw_bucketed(n_peers, **kw)


def powerlaw_1m(n_peers: int = POWERLAW_NS["powerlaw_1m"], **kw):
    return powerlaw_bucketed(n_peers, **kw)


def powerlaw_10m(n_peers: int = POWERLAW_NS["powerlaw_10m"], **kw):
    """The real 10M heavy-tailed mesh — the supervised MULTI-HOST
    scenario (scripts/run_multihost.py --engine bucketed). The bucket
    partition carries the shard alignment so any process/device count
    dividing POWERLAW_MH_ALIGN tiles every bucket; building the state
    single-process through this constructor works for tests but the
    launcher builds per-rank shards (parallel/multihost.
    init_bucketed_local) so the graph never materializes whole."""
    kw.setdefault("shard_align", POWERLAW_MH_ALIGN)
    return powerlaw_bucketed(n_peers, **kw)


def heavytail_eclipse(n_peers: int = POWERLAW_NS["powerlaw_100k"],
                      start: int = 3, end: int = 8,
                      sybil_fraction: float = 0.1, **kw):
    """Hub-targeted eclipse on the heavy-tailed underlay: powerlaw puts
    the hubs at the LOW ids — exactly the contiguous region
    EclipseWindow targets — so the window fraction is sized to cover the
    hub bucket and the sybils are drawn from the tail. The attack the
    uniform-degree eclipse scenarios cannot express: cutting the hub
    bucket severs the underlay's high-degree core."""
    import dataclasses

    from . import bucketed
    from .faults import EclipseWindow, FaultPlan
    cfg, tp, topo, subscribed = powerlaw_spec(n_peers, **kw)
    n_hub = cfg.degree_buckets[0][0]
    rng = np.random.default_rng(SEED)
    malicious = np.zeros(n_peers, dtype=bool)
    tail = np.arange(n_hub, n_peers)
    malicious[rng.choice(tail, size=min(len(tail),
                                        int(sybil_fraction * n_peers)),
                         replace=False)] = True
    cfg = dataclasses.replace(cfg, fault_plan=FaultPlan(eclipses=(
        EclipseWindow(start, end, fraction=n_hub / n_peers),)))
    return cfg, tp, bucketed.init_bucketed_state(
        cfg, topo, subscribed=subscribed, malicious=malicious)


BUCKETED_SCENARIOS = {
    "powerlaw_100k": powerlaw_100k,
    "powerlaw_1m": powerlaw_1m,
    "powerlaw_10m": powerlaw_10m,
    "heavytail_eclipse": heavytail_eclipse,
}


SCENARIOS = {
    "1k_single_topic": single_topic_1k,
    "10k_beacon": beacon_10k,
    "50k_churn": churn_50k,
    "100k_sybil": sybil_100k,
    "50k_partition": partition_50k,
    "10k_outage": outage_10k,
    "sybil_small": sybil_small,
    "partition_small": partition_small,
    "outage_small": outage_small,
    "eclipse_small": eclipse_small,
    "censor_small": censor_small,
    "flashcrowd_small": flashcrowd_small,
    "slowlink_small": slowlink_small,
    "diurnal_small": diurnal_small,
    "eclipse_50k": eclipse_50k,
    "flashcrowd_50k": flashcrowd_50k,
    "frontier_250k": frontier_250k,
    "frontier_500k": frontier_500k,
    "frontier_1m": frontier_1m,
    "frontier_4m": frontier_4m,
    "frontier_10m": frontier_10m,
}
