"""Adversary & workload library: attack scenarios with enforced contracts.

ROADMAP item 4 / ISSUE 10. The scoring/gater/PX machinery (PAPER.md
L4/L5) exists to survive adversarial meshes, and the gossipsub v1.1
hardening literature (Vyzovitis et al., "GossipSub: Attack-Resilient
Message Propagation in Filecoin and ETH2.0") evaluates routers against
eclipse, censorship, and flood attacks — not static topologies. This
module is that evaluation plane, layered on :mod:`sim.faults` (which
carries the attack schedules as jit-static ``FaultPlan`` families):

Five grounded scenario families, each a constructor returning an
:class:`AttackScenario` — ``(cfg, tp, state)`` exactly like a
``sim.scenarios`` builder, PLUS the machine-checkable **behavior
contracts** the run must satisfy and the recommended run length:

- :func:`eclipse` — sybil mesh takeover of a target peer region
  (``FaultPlan.eclipses``): the targets' honest edges are cut, heartbeat
  under-subscription grafts sybils in (GRAFT pressure), and the window
  heals through the partition redial path. Contracts: network delivery
  floor during the attack, recovery ceiling after the heal, sybils
  graylisted / honest peers not.
- :func:`censorship` — score-gamed IWANT starvation of a victim
  publisher (``FaultPlan.censorships`` + a victim-centered publish storm
  so the starvation has traffic to starve): censors advertise nothing of
  the victim's, answer no pulls for it, forward none of it — and pay in
  P7 broken promises + starved P3 credit. Contracts: the victim's topic
  keeps a delivery floor (the honest mesh routes around the censors) and
  the censors are graylisted while honest peers are not.
- :func:`flash_crowd` — hot-topic publish storm with a skewed publisher
  distribution (``FaultPlan.storms``). Contracts: delivery floor under
  load, recovery ceiling after the storm ends.
- :func:`slow_link` — heterogeneous per-edge delay/drop classes
  (``FaultPlan.slowlinks``). Contracts: delivery floor despite the slow
  tail, and NO honest peer graylisted (heterogeneous latency must not
  read as misbehavior).
- :func:`diurnal` — scheduled join/leave waves through the churn ops
  (``FaultPlan.waves``). Contracts: delivery floor across the waves,
  recovery ceiling after the last wave.

**Contracts** are declarative, JSON-serializable (journal headers,
scripts/dashboard.py), and evaluated from the per-tick telemetry row
stream (sim/telemetry.py ``HealthRecord`` — the PR 9 plane; the
graylist census is split attacker/honest by ``faults.attacker_mask``
exactly for the score-response contract). The SAME contract object runs:

- as a tier-1 test at small N (tests/test_adversary.py, the
  ``adversarial`` marker),
- per member of a fleet-swept grid (sim/fleet.py ``collect_health`` →
  scripts/sweep_scores.py contract columns),
- against a live/streamed journal (scripts/dashboard.py renders
  pass/fail/pending from the stamped schedule + rows).

Positive control: :class:`ScoreResponse` demonstrably FAILS when scoring
is disabled — a broken assertion cannot silently pass (tier-1 pinned).
"""

from __future__ import annotations

import base64
import dataclasses
import json

import numpy as np

from .config import SimConfig, TopicParams
from .faults import (
    CensorWindow,
    ChurnWave,
    EclipseWindow,
    FaultPlan,
    SlowLinkClass,
    StormWindow,
    attack_end_tick,
)
from .state import SimState, init_state
from . import topology
from .scenarios import SEED, default_topic_params

# ---------------------------------------------------------------------------
# contracts


@dataclasses.dataclass(frozen=True)
class ContractResult:
    """One contract's verdict over a row stream. ``status`` is ``"pass"``
    / ``"fail"`` / ``"pending"`` (pending = the stream hasn't reached the
    contract's decision tick yet — only possible with ``final=False``,
    the live-dashboard mode; a FINAL stream that never reaches the
    decision tick fails by name, so a too-short run can't silently
    pass)."""

    kind: str
    status: str
    detail: str
    measured: dict

    @property
    def passed(self) -> bool:
        return self.status == "pass"


def _row_delivery(row: dict, topic) -> float:
    if topic is not None:
        return row.get(f"delivery_frac_t{topic}", 0.0)
    vals, t = [], 0
    while f"delivery_frac_t{t}" in row:
        vals.append(row[f"delivery_frac_t{t}"])
        t += 1
    return sum(vals) / len(vals) if vals else 0.0


@dataclasses.dataclass(frozen=True)
class DeliveryFloor:
    """Delivery fraction must stay >= ``floor`` at EVERY tick of
    ``[start, end)`` (end None = stream end). ``topic`` restricts the
    census to one topic column (the censorship contract watches the
    victim's topic); None averages the per-topic columns."""

    floor: float
    start: int = 0
    end: int | None = None
    topic: int | None = None
    kind: str = dataclasses.field(default="delivery_floor", repr=False)

    def evaluate(self, rows: list, final: bool = True) -> ContractResult:
        end = self.end if self.end is not None else (1 << 30)
        win = [r for r in rows if self.start <= r["tick"] < end]
        if not win:
            last = max((r["tick"] for r in rows), default=-1)
            if not final and last < self.start:
                return ContractResult(self.kind, "pending",
                                      "census window not reached", {})
            return ContractResult(
                self.kind, "fail",
                f"no rows in census window [{self.start}, {end})",
                {"rows": len(rows)})
        vals = [(_row_delivery(r, self.topic), r["tick"]) for r in win]
        worst, at = min(vals)
        status = "pass" if worst >= self.floor else "fail"
        if status == "pass" and not final and self.end is not None \
                and max(r["tick"] for r in rows) < self.end - 1:
            status = "pending"
        return ContractResult(
            self.kind, status,
            f"min delivery {worst:.4f} @ tick {at} vs floor {self.floor}"
            + (f" (topic {self.topic})" if self.topic is not None else ""),
            {"min_delivery": round(worst, 4), "at_tick": at,
             "floor": self.floor})


@dataclasses.dataclass(frozen=True)
class RecoveryCeiling:
    """After the attack ends at tick ``after``, delivery must climb back
    to >= ``floor`` within ``within`` ticks — the recovery-time ceiling.
    A final stream that ends before ``after + within`` without recovering
    FAILS (the run was too short to prove recovery)."""

    after: int
    within: int
    floor: float = 0.95
    topic: int | None = None
    kind: str = dataclasses.field(default="recovery_ceiling", repr=False)

    def evaluate(self, rows: list, final: bool = True) -> ContractResult:
        post = sorted((r["tick"], _row_delivery(r, self.topic))
                      for r in rows if r["tick"] >= self.after)
        rec = next((t for t, v in post if v >= self.floor), None)
        last = max((r["tick"] for r in rows), default=-1)
        m = {"after": self.after, "within": self.within, "floor": self.floor,
             "recovered_at": rec}
        if rec is not None and rec - self.after <= self.within:
            return ContractResult(
                self.kind, "pass",
                f"recovered to >= {self.floor} at tick {rec} "
                f"({rec - self.after} ticks after heal)", m)
        if last < self.after + self.within and not final:
            return ContractResult(self.kind, "pending",
                                  "recovery window still open", m)
        worst = f"never (last tick {last})" if rec is None \
            else f"tick {rec} ({rec - self.after} > {self.within})"
        return ContractResult(
            self.kind, "fail",
            f"no recovery to >= {self.floor} within {self.within} ticks "
            f"of {self.after}: {worst}", m)


@dataclasses.dataclass(frozen=True)
class ScoreResponse:
    """The scoring machinery must RESPOND: by tick ``by``, at least
    ``attacker_frac`` of the connected attacker edges (telemetry's
    ``attacker_graylisted / attacker_edges``, attackers =
    faults.attacker_mask — sybils + censor cohorts) are below the
    graylist threshold, while honest collateral stays bounded
    (``honest_graylisted <= honest_max_frac * honest edges`` at every
    tick from ``start``). ``attacker_frac=0`` drops the attacker leg —
    the slow-link contract's shape, where the assertion is purely "no
    honest peer gets graylisted for being slow". This contract is the
    POSITIVE CONTROL of the library: with ``scoring_enabled=False``
    nothing is ever graylisted and the attacker leg must fail
    (tests/test_adversary.py pins it)."""

    by: int
    attacker_frac: float = 0.5
    honest_max_frac: float = 0.05
    start: int = 0
    kind: str = dataclasses.field(default="score_response", repr=False)

    def evaluate(self, rows: list, final: bool = True) -> ContractResult:
        resp = None
        honest_bad = []
        for r in sorted(rows, key=lambda r: r["tick"]):
            att = r.get("attacker_edges", 0)
            if resp is None and att > 0 and \
                    r.get("attacker_graylisted", 0) >= self.attacker_frac * att:
                resp = r["tick"]
            honest_edges = max(r.get("connected_edges", 0) - att, 1)
            if r["tick"] >= self.start and \
                    r.get("honest_graylisted", 0) > \
                    self.honest_max_frac * honest_edges:
                honest_bad.append(r["tick"])
        last = max((r["tick"] for r in rows), default=-1)
        m = {"by": self.by, "attacker_frac": self.attacker_frac,
             "responded_at": resp, "honest_violations": honest_bad[:8]}
        if honest_bad:
            return ContractResult(
                self.kind, "fail",
                f"honest graylisting above {self.honest_max_frac:.2%} of "
                f"honest edges at tick(s) {honest_bad[:8]}", m)
        if self.attacker_frac <= 0.0:
            return ContractResult(self.kind, "pass",
                                  "no honest peer graylisted", m)
        if resp is not None and resp <= self.by:
            return ContractResult(
                self.kind, "pass",
                f">= {self.attacker_frac:.0%} of attacker edges "
                f"graylisted by tick {resp} (<= {self.by})", m)
        if last < self.by and not final:
            return ContractResult(self.kind, "pending",
                                  "response window still open", m)
        return ContractResult(
            self.kind, "fail",
            f"attackers not graylisted to {self.attacker_frac:.0%} "
            f"by tick {self.by} (responded_at={resp})", m)


CONTRACT_KINDS = {"delivery_floor": DeliveryFloor,
                  "recovery_ceiling": RecoveryCeiling,
                  "score_response": ScoreResponse}


def contract_to_json(c) -> dict:
    d = dataclasses.asdict(c)
    d["kind"] = c.kind
    return d


# field-level validation schema for contract_from_json: name ->
# (allow_none, lo, hi, int_only). Bounds are inclusive; None disables
# that edge. Kept declarative so the fuzz surface (wrong types,
# out-of-range windows, unknown kinds/fields) is refused BY NAME, never
# a crash — the same discipline the PR 19 directive parser applies.
_CONTRACT_FIELDS = {
    "delivery_floor": {"floor": (False, 0.0, 1.0, False),
                       "start": (False, 0, None, True),
                       "end": (True, 0, None, True),
                       "topic": (True, 0, None, True)},
    "recovery_ceiling": {"after": (False, 0, None, True),
                         "within": (False, 1, None, True),
                         "floor": (False, 0.0, 1.0, False),
                         "topic": (True, 0, None, True)},
    "score_response": {"by": (False, 0, None, True),
                       "attacker_frac": (False, 0.0, 1.0, False),
                       "honest_max_frac": (False, 0.0, 1.0, False),
                       "start": (False, 0, None, True)},
}


def contract_from_json(d: dict):
    if not isinstance(d, dict):
        raise ValueError(f"contract spec must be a JSON object, "
                         f"got {type(d).__name__}")
    d = dict(d)
    kind = d.pop("kind", None)
    if not isinstance(kind, str) or kind not in CONTRACT_KINDS:
        raise ValueError(f"unknown contract kind {kind!r}; "
                         f"known: {sorted(CONTRACT_KINDS)}")
    schema = _CONTRACT_FIELDS[kind]
    unknown = sorted(set(d) - set(schema))
    if unknown:
        raise ValueError(f"contract {kind!r}: unknown field(s) {unknown}; "
                         f"known: {sorted(schema)}")
    for name, (allow_none, lo, hi, int_only) in schema.items():
        if name not in d:
            continue
        v = d[name]
        if v is None:
            if allow_none:
                continue
            raise ValueError(f"contract {kind!r}: field {name!r} "
                             f"must not be null")
        if isinstance(v, bool) or \
                not isinstance(v, int if int_only else (int, float)):
            raise ValueError(
                f"contract {kind!r}: field {name!r} must be "
                f"{'an integer' if int_only else 'a number'}, got {v!r}")
        if (lo is not None and v < lo) or (hi is not None and v > hi):
            raise ValueError(f"contract {kind!r}: field {name!r} "
                             f"out of range ({v!r} not in "
                             f"[{lo}, {'inf' if hi is None else hi}])")
    end, start = d.get("end"), d.get("start", 0)
    if kind == "delivery_floor" and end is not None and end <= start:
        raise ValueError(f"contract {kind!r}: empty census window "
                         f"[{start}, {end})")
    return CONTRACT_KINDS[kind](**d)


def contracts_to_json(contracts) -> list:
    return [contract_to_json(c) for c in contracts]


def contracts_from_json(items) -> tuple:
    return tuple(contract_from_json(d) for d in items)


def evaluate_contracts(contracts, rows: list, final: bool = True) -> list:
    """Evaluate every contract against one member's row stream (plain
    dict rows, sim/telemetry.py schema)."""
    return [c.evaluate(rows, final=final) for c in contracts]


def member_rows(rows: list, member: int) -> list:
    """One fleet member's rows out of a mixed journal/fleet row stream
    (unbatched runs carry member == -1)."""
    return [r for r in rows if r.get("member", -1) == member]


def contracts_from_schedule(windows: list) -> tuple:
    """Default contracts derived from a stamped attack schedule (the
    journal-header ``attack_windows`` list) — the dashboard's fallback
    when the run didn't stamp its scenario contracts explicitly.
    Deliberately lenient: schedule-only defaults can't know the
    scenario's tuned floors."""
    out: list = []
    ends = [w["end"] for w in windows if w.get("end") is not None]
    if ends:
        out.append(RecoveryCeiling(after=max(ends), within=15, floor=0.9))
    if any(w["kind"] in ("eclipse", "censor") for w in windows):
        out.append(ScoreResponse(by=max(ends) + 5 if ends else 1 << 30,
                                 attacker_frac=0.25, honest_max_frac=0.1))
    return tuple(out)


# ---------------------------------------------------------------------------
# streaming contract monitors (ISSUE 20): O(1)-state incremental
# evaluators, bit-exact vs the batch evaluate() at EVERY prefix of a
# tick-monotone row stream (status, detail string, measured dict — the
# tier-1 parity pins in tests/test_verdict_plane.py). Monitor state is
# JSON-serializable so checkpoint sidecars can carry it next to
# stream_offset and a SIGKILL→relaunch resumes verdict evaluation
# exactly-once. fold() never builds a ContractResult; status() is the
# per-row fast path (a few comparisons) and result() is built lazily
# only when a status transition fires.


class DeliveryFloorMonitor:
    """Streaming DeliveryFloor: running (min, argmin) over the census
    window plus the row/tick counters the batch detail strings read."""

    def __init__(self, contract: DeliveryFloor):
        self.c = contract
        self.n_rows = 0
        self.n_win = 0
        self.min_v: float | None = None
        self.min_at = -1
        self.last = -1

    def fold(self, row: dict) -> None:
        c = self.c
        self.n_rows += 1
        t = row["tick"]
        if t > self.last:
            self.last = t
        end = c.end if c.end is not None else (1 << 30)
        if c.start <= t < end:
            v = _row_delivery(row, c.topic)
            if self.min_v is None or (v, t) < (self.min_v, self.min_at):
                self.min_v, self.min_at = v, t
            self.n_win += 1

    def status(self, final: bool = False) -> str:
        c = self.c
        if self.n_win == 0:
            return "pending" if (not final and self.last < c.start) \
                else "fail"
        if self.min_v < c.floor:
            return "fail"
        if not final and c.end is not None and self.last < c.end - 1:
            return "pending"
        return "pass"

    def result(self, final: bool = False) -> ContractResult:
        c = self.c
        end = c.end if c.end is not None else (1 << 30)
        if self.n_win == 0:
            if not final and self.last < c.start:
                return ContractResult(c.kind, "pending",
                                      "census window not reached", {})
            return ContractResult(
                c.kind, "fail",
                f"no rows in census window [{c.start}, {end})",
                {"rows": self.n_rows})
        worst, at = self.min_v, self.min_at
        return ContractResult(
            c.kind, self.status(final),
            f"min delivery {worst:.4f} @ tick {at} vs floor {c.floor}"
            + (f" (topic {c.topic})" if c.topic is not None else ""),
            {"min_delivery": round(worst, 4), "at_tick": at,
             "floor": c.floor})

    def state(self) -> dict:
        return {"n_rows": self.n_rows, "n_win": self.n_win,
                "min_v": self.min_v, "min_at": self.min_at,
                "last": self.last}

    def load(self, s: dict) -> None:
        self.n_rows, self.n_win = int(s["n_rows"]), int(s["n_win"])
        self.min_v = None if s["min_v"] is None else float(s["min_v"])
        self.min_at, self.last = int(s["min_at"]), int(s["last"])


class RecoveryCeilingMonitor:
    """Streaming RecoveryCeiling: earliest post-heal tick that cleared
    the floor, plus the last tick seen."""

    def __init__(self, contract: RecoveryCeiling):
        self.c = contract
        self.rec: int | None = None
        self.last = -1

    def fold(self, row: dict) -> None:
        c = self.c
        t = row["tick"]
        if t > self.last:
            self.last = t
        if t >= c.after and _row_delivery(row, c.topic) >= c.floor:
            if self.rec is None or t < self.rec:
                self.rec = t

    def status(self, final: bool = False) -> str:
        c = self.c
        if self.rec is not None and self.rec - c.after <= c.within:
            return "pass"
        if self.last < c.after + c.within and not final:
            return "pending"
        return "fail"

    def result(self, final: bool = False) -> ContractResult:
        c = self.c
        rec = self.rec
        m = {"after": c.after, "within": c.within, "floor": c.floor,
             "recovered_at": rec}
        if rec is not None and rec - c.after <= c.within:
            return ContractResult(
                c.kind, "pass",
                f"recovered to >= {c.floor} at tick {rec} "
                f"({rec - c.after} ticks after heal)", m)
        if self.last < c.after + c.within and not final:
            return ContractResult(c.kind, "pending",
                                  "recovery window still open", m)
        worst = f"never (last tick {self.last})" if rec is None \
            else f"tick {rec} ({rec - c.after} > {c.within})"
        return ContractResult(
            c.kind, "fail",
            f"no recovery to >= {c.floor} within {c.within} ticks "
            f"of {c.after}: {worst}", m)

    def state(self) -> dict:
        return {"rec": self.rec, "last": self.last}

    def load(self, s: dict) -> None:
        self.rec = None if s["rec"] is None else int(s["rec"])
        self.last = int(s["last"])


class ScoreResponseMonitor:
    """Streaming ScoreResponse: earliest qualifying response tick plus
    the first 8 honest-collateral violation ticks (the batch evaluator
    only ever exposes ``honest_bad[:8]``, so 8 slots ARE the full
    state for a tick-monotone stream)."""

    def __init__(self, contract: ScoreResponse):
        self.c = contract
        self.resp: int | None = None
        self.honest_bad: list = []
        self.last = -1

    def fold(self, row: dict) -> None:
        c = self.c
        t = row["tick"]
        if t > self.last:
            self.last = t
        att = row.get("attacker_edges", 0)
        if att > 0 and row.get("attacker_graylisted", 0) \
                >= c.attacker_frac * att:
            if self.resp is None or t < self.resp:
                self.resp = t
        honest_edges = max(row.get("connected_edges", 0) - att, 1)
        if t >= c.start and row.get("honest_graylisted", 0) \
                > c.honest_max_frac * honest_edges \
                and len(self.honest_bad) < 8:
            self.honest_bad.append(t)

    def status(self, final: bool = False) -> str:
        c = self.c
        if self.honest_bad:
            return "fail"
        if c.attacker_frac <= 0.0:
            return "pass"
        if self.resp is not None and self.resp <= c.by:
            return "pass"
        if self.last < c.by and not final:
            return "pending"
        return "fail"

    def result(self, final: bool = False) -> ContractResult:
        c = self.c
        m = {"by": c.by, "attacker_frac": c.attacker_frac,
             "responded_at": self.resp,
             "honest_violations": list(self.honest_bad)}
        if self.honest_bad:
            return ContractResult(
                c.kind, "fail",
                f"honest graylisting above {c.honest_max_frac:.2%} of "
                f"honest edges at tick(s) {self.honest_bad}", m)
        if c.attacker_frac <= 0.0:
            return ContractResult(c.kind, "pass",
                                  "no honest peer graylisted", m)
        if self.resp is not None and self.resp <= c.by:
            return ContractResult(
                c.kind, "pass",
                f">= {c.attacker_frac:.0%} of attacker edges "
                f"graylisted by tick {self.resp} (<= {c.by})", m)
        if self.last < c.by and not final:
            return ContractResult(c.kind, "pending",
                                  "response window still open", m)
        return ContractResult(
            c.kind, "fail",
            f"attackers not graylisted to {c.attacker_frac:.0%} "
            f"by tick {c.by} (responded_at={self.resp})", m)

    def state(self) -> dict:
        return {"resp": self.resp, "honest_bad": list(self.honest_bad),
                "last": self.last}

    def load(self, s: dict) -> None:
        self.resp = None if s["resp"] is None else int(s["resp"])
        self.honest_bad = [int(t) for t in s["honest_bad"]]
        self.last = int(s["last"])


MONITOR_KINDS = {"delivery_floor": DeliveryFloorMonitor,
                 "recovery_ceiling": RecoveryCeilingMonitor,
                 "score_response": ScoreResponseMonitor}


def monitor_for(contract):
    return MONITOR_KINDS[contract.kind](contract)


class ContractMonitors:
    """A contract set folded one row at a time, emitting VERDICT
    TRANSITION events — the journaled ``contract_verdict`` stream. Each
    event carries a deterministic id (contract index, transition seq,
    status, decided tick); the tick is a pure function of the row
    stream, NOT of chunking, so a relaunch that re-folds rows past its
    checkpoint re-derives byte-identical events and read-side dedup
    (telemetry.read_journal / the dashboard tailer) absorbs any note
    journaled before the crash — exactly-once without a write-side
    transaction."""

    STATE_VERSION = 1

    def __init__(self, contracts):
        self.contracts = tuple(contracts)
        self.monitors = [monitor_for(c) for c in self.contracts]
        self.statuses = ["pending"] * len(self.monitors)
        self.seqs = [0] * len(self.monitors)
        self.finalized = False

    def fold_rows(self, rows) -> list:
        """Fold rows in stream order; return the transition events they
        produced (possibly none), in firing order."""
        events = []
        for row in rows:
            t = row["tick"]
            for i, mon in enumerate(self.monitors):
                mon.fold(row)
                st = mon.status(final=False)
                if st != self.statuses[i]:
                    self.statuses[i] = st
                    self.seqs[i] += 1
                    events.append(self._event(i, mon.result(final=False),
                                              t))
        return events

    def finalize(self) -> list:
        """The true-run-end pass: resolve every still-pending contract
        with ``final=True`` semantics (a too-short stream fails by
        name). Idempotent across a relaunch — re-finalizing re-derives
        the same ids, which read-side dedup absorbs."""
        self.finalized = True
        events = []
        for i, mon in enumerate(self.monitors):
            st = mon.status(final=True)
            if st != self.statuses[i]:
                self.statuses[i] = st
                self.seqs[i] += 1
                events.append(self._event(i, mon.result(final=True),
                                          mon.last, final=True))
        return events

    def _event(self, i: int, res: ContractResult, tick, final=False):
        seq = self.seqs[i]
        return {"contract": i, "kind": res.kind, "seq": seq,
                "status": res.status, "detail": res.detail,
                "measured": res.measured, "tick": int(tick),
                "final": bool(final),
                "id": f"c{i}.s{seq}.{res.status}@{int(tick)}"}

    def results(self, final: bool = False) -> list:
        return [m.result(final=final) for m in self.monitors]

    @property
    def any_failed(self) -> bool:
        return "fail" in self.statuses

    # -- checkpoint-sidecar serialization ---------------------------------
    # sidecar values must be whitespace-free (checkpoint.sidecar_meta
    # splits the file on whitespace), hence the base64url token form

    def to_state(self) -> dict:
        return {"v": self.STATE_VERSION,
                "contracts": contracts_to_json(self.contracts),
                "statuses": list(self.statuses),
                "seqs": list(self.seqs),
                "finalized": self.finalized,
                "monitors": [m.state() for m in self.monitors]}

    @classmethod
    def from_state(cls, state: dict, contracts=None) -> "ContractMonitors":
        cs = contracts_from_json(state["contracts"])
        if contracts is not None and tuple(contracts) != cs:
            raise ValueError(
                "checkpointed monitor state does not match the active "
                "contract set; refusing a silent verdict reset")
        self = cls(cs)
        self.statuses = [str(s) for s in state["statuses"]]
        self.seqs = [int(s) for s in state["seqs"]]
        self.finalized = bool(state.get("finalized", False))
        for mon, s in zip(self.monitors, state["monitors"]):
            mon.load(s)
        return self

    def state_token(self) -> str:
        raw = json.dumps(self.to_state(),
                         separators=(",", ":")).encode("utf-8")
        return base64.urlsafe_b64encode(raw).decode("ascii")

    @classmethod
    def from_token(cls, token: str, contracts=None) -> "ContractMonitors":
        raw = base64.urlsafe_b64decode(token.encode("ascii"))
        return cls.from_state(json.loads(raw.decode("utf-8")),
                              contracts=contracts)


# ---------------------------------------------------------------------------
# the scenario families


class AttackScenario(tuple):
    """``(cfg, tp, state, contracts, n_ticks, name)`` — the first three
    elements are exactly a ``sim.scenarios`` builder's return (so
    ``scenario[:3]`` drops into every existing runner), ``contracts`` is
    the tuple of behavior contracts the run must satisfy over
    ``n_ticks`` ticks."""

    __slots__ = ()

    def __new__(cls, cfg, tp, state, contracts, n_ticks, name):
        return tuple.__new__(cls, (cfg, tp, state, contracts, n_ticks, name))

    cfg = property(lambda s: s[0])
    tp = property(lambda s: s[1])
    state = property(lambda s: s[2])
    contracts = property(lambda s: s[3])
    n_ticks = property(lambda s: s[4])
    name = property(lambda s: s[5])


def _attack_cfg(n_peers: int, k_slots: int, n_topics: int, plan: FaultPlan,
                **overrides) -> SimConfig:
    """The shared adversarial config shape: full scoring with the
    sybil_100k-style shallow thresholds (attacks must be able to MOVE the
    graylist census within a small-N, tens-of-ticks run), PX + churn so
    cut edges have a reconnect path, score retention covering the attack
    windows — and the plan itself (``fault_plan`` is owned here, so a
    caller can never build an attack config that silently drops its
    attack)."""
    base = dict(
        n_peers=n_peers, k_slots=k_slots, n_topics=n_topics, msg_window=64,
        publishers_per_tick=8, prop_substeps=8,
        scoring_enabled=True, behaviour_penalty_weight=-10.0,
        behaviour_penalty_threshold=2.0, behaviour_penalty_decay=0.99,
        gossip_threshold=-10.0, publish_threshold=-50.0,
        graylist_threshold=-100.0,
        churn_disconnect_prob=0.01, churn_reconnect_prob=0.2,
        px_enabled=True, accept_px_threshold=-5.0, retain_score_ticks=600)
    base["fault_plan"] = plan
    base.update(overrides)
    return SimConfig(**base)


def eclipse(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
            sybil_fraction: float = 0.25, target_fraction: float = 0.12,
            start: int = 10, end: int = 25, n_ticks: int = 40,
            n_sybil_ips: int = 8, **cfg_kw) -> AttackScenario:
    """Eclipse: a sybil population (invalid publishes, IHAVE floods,
    unanswered IWANTs — the spam-actor set) plus an
    :class:`~.faults.EclipseWindow` cutting the target region's honest
    edges for ticks [start, end). During the window the targets' meshes
    fill with sybils; scoring must graylist them (P4 + P7 + P6) and the
    heal must restore delivery."""
    rng = np.random.default_rng(SEED)
    malicious = rng.random(n_peers) < sybil_fraction
    # the target region is id-contiguous (faults.py eclipse semantics):
    # keep it honest so the cut has honest edges to cut
    n_tgt = max(1, int(np.ceil(target_fraction * n_peers)))
    malicious[:n_tgt] = False
    ip_group = np.arange(n_peers, dtype=np.int32)
    ip_group[malicious] = n_peers + rng.integers(
        0, n_sybil_ips, int(malicious.sum())).astype(np.int32)
    _, ip_group = np.unique(ip_group, return_inverse=True)
    ip_group = ip_group.astype(np.int32)
    plan = FaultPlan(eclipses=(EclipseWindow(start, end,
                                             fraction=target_fraction),))
    cfg = _attack_cfg(n_peers, k_slots, 1, plan,
                      ip_colocation_factor_weight=-50.0,
                      ip_colocation_factor_threshold=4,
                      n_ip_groups=int(ip_group.max()) + 1, **cfg_kw)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    state = init_state(cfg, topo, malicious=malicious, ip_group=ip_group)
    contracts = (
        # the network at large must ride out the regional cut
        DeliveryFloor(floor=0.70, start=start, end=end),
        # and the heal must restore near-full delivery quickly
        RecoveryCeiling(after=end, within=10, floor=0.95),
        # sybils graylisted by the time the window closes, honest spared
        ScoreResponse(by=end, attacker_frac=0.5, honest_max_frac=0.05),
    )
    return AttackScenario(cfg, default_topic_params(1), state, contracts,
                          n_ticks, "eclipse")


def censorship(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
               censor_fraction: float = 0.4, victim: int = 0,
               start: int = 8, end: int = 30, n_ticks: int = 40,
               skew: float = 0.8, **cfg_kw) -> AttackScenario:
    """Censorship: a censor cohort starves the victim publisher's
    messages (:class:`~.faults.CensorWindow`) while a victim-centered
    :class:`~.faults.StormWindow` gives the starvation real traffic to
    starve (hot=1 → the victim publishes ``skew`` of the window's
    traffic). The honest mesh must route around the censors and the
    censors must pay: every unanswered pull is a P7 broken promise."""
    if victim != 0:
        # the victim-centered storm publishes from the HOT set = the
        # lowest peer ids (StormWindow semantics), and hot=1 makes that
        # exactly peer 0 — a victim elsewhere would be censored while
        # peer 0 carries the storm, silently measuring the wrong peer
        raise ValueError(
            "censorship(): the victim-centered storm (StormWindow hot=1) "
            "publishes from peer 0, so victim must be 0; relabel peers "
            "instead of moving the victim")
    # the cohort must be large enough to OWN eager paths: a message is
    # missed eagerly only when every mesh sender on it censors, and only
    # a miss sends the IWANT whose unanswered promise prices the attack
    # (an eagerly saturated mesh never pulls, and an unasked censor is
    # indistinguishable from an honest peer)
    plan = FaultPlan(
        censorships=(CensorWindow(start, end, fraction=censor_fraction,
                                  victim=victim),),
        storms=(StormWindow(start, end, hot=1, skew=skew, topic=0),))
    # shallow thresholds + zero P7 activation: a censor's price is a few
    # broken promises per asking edge (the asker stops pulling from it
    # once it sinks below the gossip threshold, capping the penalty), so
    # the graylist line must sit where that price can reach it — the
    # scenario-scale analogue of tuning PeerScoreThresholds to the
    # topic's traffic rate
    kw = dict(behaviour_penalty_threshold=0.0, gossip_threshold=-10.0,
              publish_threshold=-20.0, graylist_threshold=-30.0)
    kw.update(cfg_kw)
    cfg = _attack_cfg(n_peers, k_slots, 1, plan, **kw)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    state = init_state(cfg, topo)
    # P3 is the defense that prices this attack (score.go:949-981 mesh
    # delivery deficit): with the victim at `skew` of the window's
    # traffic, a censor's mesh-delivery credit runs at ~(1-skew) of an
    # honest peer's, so a deliveries threshold BETWEEN the two rates
    # (honest ~2x publish rate at decay 0.5, censor ~2x(1-skew)x rate)
    # puts every censoring mesh edge in squared deficit while honest
    # edges keep full margin — the per-topic tuning the Eth2 scoring
    # shape applies to its high-rate topics. P7 rides along: the few
    # wholly-censor-surrounded peers' pulls break promises too.
    from ..core.params import TopicScoreParams
    tp = TopicParams.from_topic_params([TopicScoreParams(
        topic_weight=1.0, time_in_mesh_weight=0.01,
        time_in_mesh_quantum=1.0, time_in_mesh_cap=3600.0,
        first_message_deliveries_weight=1.0,
        first_message_deliveries_decay=0.5,
        first_message_deliveries_cap=100.0,
        mesh_message_deliveries_weight=-10.0,
        mesh_message_deliveries_decay=0.5,
        mesh_message_deliveries_cap=100.0,
        mesh_message_deliveries_threshold=6.0,
        mesh_message_deliveries_window=0.01,
        mesh_message_deliveries_activation=5.0,
        mesh_failure_penalty_weight=-10.0, mesh_failure_penalty_decay=0.5,
        invalid_message_deliveries_weight=-10.0,
        invalid_message_deliveries_decay=0.9,
    )])
    contracts = (
        # the victim's topic keeps delivering despite the censors
        DeliveryFloor(floor=0.85, start=start, end=end, topic=0),
        # censors graylisted (P3 deficit -> heartbeat eviction), honest
        # spared entirely. The graylist residence is transient per edge
        # (eviction converts the deficit to a decaying failure penalty),
        # so the bar is the synchronized deficit SPIKE a few ticks after
        # activation — measured ~14% of censor edges at this shape —
        # not a steady majority
        ScoreResponse(by=end, attacker_frac=0.10, honest_max_frac=0.01,
                      start=start),
    )
    return AttackScenario(cfg, tp, state, contracts,
                          n_ticks, "censorship")


def flash_crowd(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
                start: int = 10, end: int = 25, hot: int = 8,
                skew: float = 0.95, n_ticks: int = 40,
                **cfg_kw) -> AttackScenario:
    """Flash crowd: a hot-topic publish storm from a skewed publisher
    set (:class:`~.faults.StormWindow`) at double the ambient publish
    rate. The mesh must absorb the load (delivery floor) and settle back
    once the crowd disperses (recovery ceiling)."""
    plan = FaultPlan(storms=(StormWindow(start, end, hot=hot, skew=skew,
                                         topic=0),))
    cfg = _attack_cfg(n_peers, k_slots, 2, plan,
                      publishers_per_tick=16, **cfg_kw)
    rng = np.random.default_rng(SEED)
    subscribed = np.ones((n_peers, 2), dtype=bool)
    subscribed[:, 1] = rng.random(n_peers) < 0.4   # a bystander subnet
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    state = init_state(cfg, topo, subscribed=subscribed)
    # Eth2-style per-topic tuning: only the HOT topic carries the mesh-
    # delivery-deficit penalty (P3). A storm starves the bystander topic
    # of window slots, and an idle topic with an MMD threshold penalizes
    # its whole mesh into mutual pruning + 60-tick backoff — the known
    # idle-topic footgun real deployments configure away (attestation
    # subnets carry MMD weights, voluntary_exit-class topics don't).
    base = default_topic_params(2)
    zeros2 = base.mesh_message_deliveries_weight * \
        np.asarray([1.0, 0.0], np.float32)
    tp = base._replace(
        mesh_message_deliveries_weight=zeros2,
        mesh_failure_penalty_weight=base.mesh_failure_penalty_weight
        * np.asarray([1.0, 0.0], np.float32))
    contracts = (
        DeliveryFloor(floor=0.90, start=start, end=end),
        RecoveryCeiling(after=end, within=8, floor=0.97),
    )
    return AttackScenario(cfg, tp, state, contracts,
                          n_ticks, "flash_crowd")


def slow_link(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
              fraction: float = 0.3, period: int = 3, drop: float = 0.05,
              n_ticks: int = 40, **cfg_kw) -> AttackScenario:
    """Slow links: a heterogeneous link model
    (:class:`~.faults.SlowLinkClass` — a third of the edges open their
    data plane 1-in-``period`` ticks and drop ``drop`` even then). The
    router's gossip pull path must compensate (delivery floor), and —
    the robustness leg — peers behind slow links must NOT end up
    graylisted: latency is not misbehavior."""
    plan = FaultPlan(slowlinks=(SlowLinkClass(fraction=fraction,
                                              period=period, drop=drop),))
    cfg = _attack_cfg(n_peers, k_slots, 1, plan, **cfg_kw)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    state = init_state(cfg, topo)
    contracts = (
        DeliveryFloor(floor=0.90, start=10),
        # no attacker leg (attacker_frac=0): the whole assertion is that
        # heterogeneous RTT produces NO honest graylisting
        ScoreResponse(by=0, attacker_frac=0.0, honest_max_frac=0.02),
    )
    return AttackScenario(cfg, default_topic_params(1), state, contracts,
                          n_ticks, "slow_link")


def diurnal(n_peers: int = 512, k_slots: int = 16, degree: int = 6,
            period: int = 15, duty: int = 5, until: int = 51,
            fraction: float = 0.25, phase: int = 6, n_ticks: int = 55,
            **cfg_kw) -> AttackScenario:
    """Diurnal churn: the same quarter of the network leaves for the
    first ``duty`` ticks of every ``period``-tick cycle and rejoins
    through the churn/backoff/retention path
    (:class:`~.faults.ChurnWave`). The mesh must re-knit around each
    wave (delivery floor over the whole schedule — the dark cohort's
    undelivered rows ARE the dip being bounded) and recover fully after
    the last wave."""
    plan = FaultPlan(waves=(ChurnWave(period=period, duty=duty,
                                      until=until, fraction=fraction,
                                      phase=phase),))
    cfg = _attack_cfg(n_peers, k_slots, 1, plan, **cfg_kw)
    topo = topology.sparse(n_peers, k_slots, degree=degree, seed=SEED)
    state = init_state(cfg, topo)
    last_end = attack_end_tick(plan)
    contracts = (
        # the dark cohort's own undelivered rows are the dip being
        # bounded: fraction of the census goes dark every cycle, so the
        # floor sits under 1 - fraction with catch-up margin
        DeliveryFloor(floor=0.45, start=phase),
        RecoveryCeiling(after=last_end, within=10, floor=0.95),
    )
    return AttackScenario(cfg, default_topic_params(1), state, contracts,
                          n_ticks, "diurnal")


# name -> constructor; the *_small names sim/scenarios.py registers are
# thin wrappers over these (scenario[:3])
FAMILIES = {
    "eclipse": eclipse,
    "censorship": censorship,
    "flash_crowd": flash_crowd,
    "slow_link": slow_link,
    "diurnal": diurnal,
}

# the sweep/test registry: scenario-registry name -> AttackScenario
# builder (same names as sim/scenarios.SCENARIOS entries)
ATTACKS = {
    "eclipse_small": eclipse,
    "censor_small": censorship,
    "flashcrowd_small": flash_crowd,
    "slowlink_small": slow_link,
    "diurnal_small": diurnal,
}


# ---------------------------------------------------------------------------
# running + evaluating


@dataclasses.dataclass
class AttackReport:
    """One scenario run's outcome: final state, the telemetry row stream
    the contracts were judged on, and the per-contract results."""

    name: str
    state: SimState
    rows: list
    results: list
    fault_flags: int

    @property
    def passed(self) -> bool:
        return all(r.passed for r in self.results)

    def summary(self) -> dict:
        return {"scenario": self.name, "passed": self.passed,
                "fault_flags": self.fault_flags,
                "contracts": [{"kind": r.kind, "status": r.status,
                               "detail": r.detail} for r in self.results]}


def run_with_contracts(scn: AttackScenario, key=None,
                       n_ticks: int | None = None) -> AttackReport:
    """Run one scenario end-to-end on the telemetry lane
    (``engine.run_keys(telemetry=True)`` — the same device-side reduction
    every execution plane streams) and evaluate its contracts on the
    resulting rows. The tier-1 entry point; the fleet and journal planes
    evaluate the same contracts via :func:`evaluate_contracts`."""
    import jax

    from . import telemetry
    from .engine import run_keys

    key = jax.random.PRNGKey(0) if key is None else key
    ticks = n_ticks if n_ticks is not None else scn.n_ticks
    keys = jax.random.split(key, ticks)
    state, health = run_keys(scn.state, scn.cfg, scn.tp, keys,
                             telemetry=True)
    mat, cols = telemetry.records_to_rows(health)
    rows = telemetry.rows_to_dicts(mat, cols)
    results = evaluate_contracts(scn.contracts, rows, final=True)
    return AttackReport(scn.name, state, rows, results,
                        int(np.asarray(state.fault_flags)))
