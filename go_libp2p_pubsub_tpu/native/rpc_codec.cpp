// Native RPC wire scanner: uvarint-delimited pb/rpc.proto frame streams ->
// per-frame statistics + per-message tensors.
//
// The C++ twin of walking pb/codec.py `read_frames` output in Python. The
// wire format is the reference's stream framing (comm.go:157-171: uvarint
// length prefix, max 1 MiB payload) over the proto2 RPC schema
// (pb/rpc.proto:5-57): RPC{subscriptions=1, publish=2, control=3},
// Message{from=1, data=2, seqno=3, topic=4, signature=5, key=6},
// ControlMessage{ihave=1{topic=1, mids=2}, iwant=2{mids=1},
// graft=3{topic=1}, prune=4{topic=1, peers=2{peer=1, record=2}, backoff=3}}.
//
// Bulk host-side RPC streams (interop captures, adversarial load fixtures,
// differential-test corpora) are parsed here without instantiating
// per-frame Python objects; pb/native_rpc.py binds it via ctypes with the
// pure-Python scan as the documented fallback, and
// tests/test_native_codec.py enforces array-for-array equality.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

bool read_uvarint(const uint8_t* buf, size_t len, size_t* pos, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = buf[(*pos)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

struct Field {
  uint32_t num;
  uint32_t wire;
  uint64_t varint;      // wire 0
  const uint8_t* p;     // wire 2
  uint64_t len;         // wire 2
};

// Walk one proto2 message's fields; returns false on malformed input.
bool next_field(const uint8_t* buf, size_t len, size_t* pos, Field* f) {
  if (*pos >= len) return false;
  uint64_t key;
  if (!read_uvarint(buf, len, pos, &key)) return false;
  f->num = (uint32_t)(key >> 3);
  f->wire = (uint32_t)(key & 7);
  f->p = nullptr;
  f->len = 0;
  f->varint = 0;
  switch (f->wire) {
    case 0:
      return read_uvarint(buf, len, pos, &f->varint);
    case 2: {
      uint64_t l;
      if (!read_uvarint(buf, len, pos, &l)) return false;
      if (l > len - *pos) return false;
      f->p = buf + *pos;
      f->len = l;
      *pos += l;
      return true;
    }
    case 1:
      if (len - *pos < 8) return false;
      *pos += 8;
      return true;
    case 5:
      if (len - *pos < 4) return false;
      *pos += 4;
      return true;
    default:
      return false;
  }
}

struct Scanner {
  std::vector<int64_t> stats;   // 8 per frame
  std::vector<int64_t> msgs;    // 4 per publish message
  std::vector<std::string> topics;
  std::unordered_map<std::string, int64_t> topic_ids;

  int64_t intern(const uint8_t* p, uint64_t len) {
    std::string t((const char*)p, len);
    auto it = topic_ids.find(t);
    if (it != topic_ids.end()) return it->second;
    int64_t id = (int64_t)topics.size();
    topics.push_back(t);
    topic_ids.emplace(std::move(t), id);
    return id;
  }

  // counts message ids (field `mid_field`) inside an ihave/iwant body
  static bool count_mids(const uint8_t* p, uint64_t len, uint32_t mid_field,
                         int64_t* out) {
    size_t pos = 0;
    Field f;
    while (pos < len) {
      if (!next_field(p, len, &pos, &f)) return false;
      if (f.num == mid_field && f.wire == 2) (*out)++;
    }
    return true;
  }

  bool scan_message(const uint8_t* p, uint64_t len, int64_t frame) {
    size_t pos = 0;
    Field f;
    int64_t topic_id = -1, data_len = 0;
    uint64_t seqno = 0;
    while (pos < len) {
      if (!next_field(p, len, &pos, &f)) return false;
      if (f.wire != 2) continue;
      if (f.num == 2) {
        data_len = (int64_t)f.len;
      } else if (f.num == 3) {
        // big-endian seqno bytes (pubsub.go:1341-1346), up to 8 bytes
        seqno = 0;
        for (uint64_t i = 0; i < f.len && i < 8; i++)
          seqno = (seqno << 8) | f.p[i];
      } else if (f.num == 4 && f.len > 0) {
        // empty topic stays -1: the Python twin decodes proto2 absent and
        // present-but-empty to the same "" and interns neither, so the
        // native path must not invent a topic id for it
        topic_id = intern(f.p, f.len);
      }
    }
    msgs.push_back(frame);
    msgs.push_back(topic_id);
    msgs.push_back(data_len);
    msgs.push_back((int64_t)seqno);
    return true;
  }

  bool scan_control(const uint8_t* p, uint64_t len, int64_t* st) {
    size_t pos = 0;
    Field f;
    while (pos < len) {
      if (!next_field(p, len, &pos, &f)) return false;
      if (f.wire != 2) continue;
      switch (f.num) {
        case 1:
          if (!count_mids(f.p, f.len, 2, &st[3])) return false;
          break;
        case 2:
          if (!count_mids(f.p, f.len, 1, &st[4])) return false;
          break;
        case 3:
          st[5]++;
          break;
        case 4: {
          st[6]++;
          size_t ppos = 0;
          Field pf;
          while (ppos < f.len) {
            if (!next_field(f.p, f.len, &ppos, &pf)) return false;
            if (pf.num == 2 && pf.wire == 2) st[7]++;  // PX records
          }
          break;
        }
      }
    }
    return true;
  }

  // returns 0 ok, 2 malformed framing/proto, 3 oversize frame
  int scan(const uint8_t* buf, size_t len, uint64_t max_frame) {
    size_t pos = 0;
    int64_t frame = 0;
    while (pos < len) {
      uint64_t flen;
      if (!read_uvarint(buf, len, &pos, &flen)) return 2;
      if (flen > len - pos) return 2;
      if (max_frame && flen > max_frame) return 3;
      const uint8_t* fp = buf + pos;
      pos += flen;
      stats.insert(stats.end(), 8, 0);
      int64_t* st = &stats[stats.size() - 8];
      size_t mp = 0;
      Field f;
      while (mp < flen) {
        if (!next_field(fp, flen, &mp, &f)) return 2;
        if (f.wire != 2) continue;
        if (f.num == 1) {
          st[0]++;
        } else if (f.num == 2) {
          st[1]++;
          if (!scan_message(f.p, f.len, frame)) return 2;
          st[2] += msgs[msgs.size() - 2];  // the row's data_len
        } else if (f.num == 3) {
          if (!scan_control(f.p, f.len, st)) return 2;
        }
      }
      frame++;
    }
    return 0;
  }
};

char* pack_topics(const std::vector<std::string>& topics, long* n_bytes) {
  size_t total = 0;
  for (const auto& t : topics) total += 4 + t.size();
  char* out = (char*)malloc(total ? total : 1);
  size_t off = 0;
  for (const auto& t : topics) {
    uint32_t l = (uint32_t)t.size();
    memcpy(out + off, &l, 4);
    off += 4;
    memcpy(out + off, t.data(), t.size());
    off += t.size();
  }
  *n_bytes = (long)total;
  return out;
}

}  // namespace

extern "C" {

// Scan a uvarint-delimited RPC frame stream.
// Outputs (malloc'd; caller frees via rpc_codec_free):
//   *stats  [n_frames, 8] int64: subs, publish, publish_data_bytes,
//           ihave_ids, iwant_ids, grafts, prunes, px_records
//   *msgs   [n_msgs, 4] int64: frame_idx, topic_id, data_len, seqno
//   *topics length-prefixed (u32 LE) topic strings in topic_id order
// Returns 0 ok, 2 malformed, 3 frame over max_frame (0 = unlimited).
int rpc_codec_scan(const uint8_t* buf, long len, long max_frame,
                   int64_t** stats, long* n_frames,
                   int64_t** msgs, long* n_msgs,
                   char** topics, long* topics_bytes) {
  Scanner sc;
  int rc = sc.scan(buf, (size_t)len, (uint64_t)max_frame);
  if (rc != 0) return rc;
  *n_frames = (long)(sc.stats.size() / 8);
  *stats = (int64_t*)malloc(sc.stats.size() * sizeof(int64_t) + 1);
  memcpy(*stats, sc.stats.data(), sc.stats.size() * sizeof(int64_t));
  *n_msgs = (long)(sc.msgs.size() / 4);
  *msgs = (int64_t*)malloc(sc.msgs.size() * sizeof(int64_t) + 1);
  memcpy(*msgs, sc.msgs.data(), sc.msgs.size() * sizeof(int64_t));
  *topics = pack_topics(sc.topics, topics_bytes);
  return 0;
}

void rpc_codec_free(void* p) { free(p); }

}  // extern "C"
