// Native trace codec: varint-delimited pb/trace TraceEvent stream ->
// tensorized replay op arrays.
//
// This is the C++ twin of go_libp2p_pubsub_tpu/trace/replay.py
// `tensorize_trace` (which mirrors the reference's delivery-record state
// machine, score.go:840-877) plus the wire walk of pb/trace.proto
// (pb/codec.py schemas). It exists for the host-side bottleneck flagged in
// SURVEY.md §7 "Host/device boundary in trace replay": 100k-peer traces are
// hundreds of MB; parsing + tensorizing them in Python dominates replay
// time, so the framework ships this native path (loaded via ctypes, with
// the Python implementation as the documented fallback — see
// trace/native.py).
//
// Contract: byte-for-byte identical op streams to the Python tensorizer
// (tests/test_native_codec.py enforces array equality).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---- op codes (trace/replay.py) ----
enum Op {
  OP_NOP = 0, OP_DECAY = 1, OP_GRAFT = 2, OP_PRUNE = 3, OP_FIRST = 4,
  OP_DUP = 5, OP_INVALID = 6, OP_PENALTY = 7, OP_JOIN = 8, OP_LEAVE = 9,
  OP_PUBLISH = 10, OP_DELIVER = 11, OP_CONNECT = 12, OP_DISCONNECT = 13,
};

// ---- trace event types (pb/codec.py TRACE_TYPES) ----
enum EvType {
  EV_PUBLISH_MESSAGE = 0, EV_REJECT_MESSAGE = 1, EV_DUPLICATE_MESSAGE = 2,
  EV_DELIVER_MESSAGE = 3, EV_ADD_PEER = 4, EV_REMOVE_PEER = 5,
  EV_RECV_RPC = 6, EV_SEND_RPC = 7, EV_DROP_RPC = 8, EV_JOIN = 9,
  EV_LEAVE = 10, EV_GRAFT = 11, EV_PRUNE = 12,
};

// delivery-record states (score.go:90-120)
enum RecStatus { ST_UNKNOWN = 0, ST_VALID, ST_INVALID, ST_THROTTLED, ST_IGNORED };

struct Record {
  int status = ST_UNKNOWN;
  std::vector<std::string> peers;  // insertion-ordered, may hold unknown ids
  double validated = 0.0;
};

struct Slice {
  const uint8_t* p = nullptr;
  size_t len = 0;
  bool empty() const { return p == nullptr; }
  std::string str() const { return std::string((const char*)p, len); }
};

bool read_uvarint(const uint8_t* buf, size_t len, size_t* pos, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < len && shift < 64) {
    uint8_t b = buf[(*pos)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) { *out = v; return true; }
    shift += 7;
  }
  return false;
}

// walk a proto2 message; callback per (field, wire, varint value | slice).
// Length checks are overflow-safe: lengths are compared against the
// remaining byte count, never added to pos first.
template <typename F>
bool walk_fields(const uint8_t* buf, size_t len, F&& cb) {
  size_t pos = 0;
  while (pos < len) {
    uint64_t key;
    if (!read_uvarint(buf, len, &pos, &key)) return false;
    uint32_t field = (uint32_t)(key >> 3), wire = (uint32_t)(key & 7);
    if (wire == 0) {
      uint64_t v;
      if (!read_uvarint(buf, len, &pos, &v)) return false;
      cb(field, wire, v, Slice{});
    } else if (wire == 2) {
      uint64_t l;
      if (!read_uvarint(buf, len, &pos, &l)) return false;
      if (l > len - pos) return false;
      cb(field, wire, 0, Slice{buf + pos, (size_t)l});
      pos += l;
    } else if (wire == 5) {
      if (len - pos < 4) return false;
      pos += 4;
    } else if (wire == 1) {
      if (len - pos < 8) return false;
      pos += 8;
    } else {
      return false;
    }
  }
  return true;
}

struct Payload {
  Slice mid, peer, topic, reason;
};

// payload sub-message schemas (pb/codec.py _PAYLOAD_SCHEMAS). Field numbers:
//   publishMessage: 1 mid, 2 topic
//   rejectMessage: 1 mid, 2 peer, 3 reason, 4 topic
//   duplicateMessage: 1 mid, 2 peer, 3 topic
//   deliverMessage: 1 mid, 2 topic, 3 peer
//   addPeer: 1 peer, 2 proto ; removePeer: 1 peer
//   join: 1 topic ; leave: 2 topic (the proto's one oddity, trace.proto:94)
//   graft/prune: 1 peer, 2 topic
bool parse_payload(int ev_type, Slice s, Payload* out_p) {
  Payload& out = *out_p;
  return walk_fields(s.p, s.len, [&](uint32_t f, uint32_t w, uint64_t, Slice v) {
    if (w != 2) return;
    switch (ev_type) {
      case EV_PUBLISH_MESSAGE:
        if (f == 1) out.mid = v; else if (f == 2) out.topic = v;
        break;
      case EV_REJECT_MESSAGE:
        if (f == 1) out.mid = v; else if (f == 2) out.peer = v;
        else if (f == 3) out.reason = v; else if (f == 4) out.topic = v;
        break;
      case EV_DUPLICATE_MESSAGE:
        if (f == 1) out.mid = v; else if (f == 2) out.peer = v;
        else if (f == 3) out.topic = v;
        break;
      case EV_DELIVER_MESSAGE:
        if (f == 1) out.mid = v; else if (f == 2) out.topic = v;
        else if (f == 3) out.peer = v;
        break;
      case EV_ADD_PEER:
      case EV_REMOVE_PEER:
        if (f == 1) out.peer = v;
        break;
      case EV_JOIN:
        if (f == 1) out.topic = v;
        break;
      case EV_LEAVE:
        if (f == 2) out.topic = v;
        break;
      case EV_GRAFT:
      case EV_PRUNE:
        if (f == 1) out.peer = v; else if (f == 2) out.topic = v;
        break;
      default:
        break;
    }
  });
}

// rejection-reason strings (trace/events.py, tracer.go:27-39)
bool is_sig_reject(const std::string& r) {
  return r == "missing signature" || r == "invalid signature" ||
         r == "unexpected signature" || r == "unexpected auth info" ||
         r == "self originated message";
}
bool is_silent_reject(const std::string& r) {
  return r == "blacklisted peer" || r == "blacklisted source" ||
         r == "validation queue full";
}

struct Tensorizer {
  std::unordered_map<std::string, int32_t> peer_index, topic_index;
  std::unordered_map<std::string, int32_t> mid_slot;
  std::vector<std::string> mid_order;
  std::unordered_map<std::string, Record> records;  // key: observer \x00 mid
  std::vector<int32_t> ops;  // interleaved (op, a, b, c)
  const double* dup_window = nullptr;
  double decay_interval = 1.0;
  double next_decay = 1.0;
  long msg_window = 0;

  void emit(int32_t op, int32_t a, int32_t b, int32_t c) {
    ops.push_back(op); ops.push_back(a); ops.push_back(b); ops.push_back(c);
  }

  int32_t peer_of(Slice s) {
    if (s.empty()) return -1;
    auto it = peer_index.find(s.str());
    return it == peer_index.end() ? -1 : it->second;
  }
  int32_t topic_of(Slice s) {
    if (s.empty()) return -1;
    auto it = topic_index.find(s.str());
    return it == topic_index.end() ? -1 : it->second;
  }
  int32_t slot_of(const std::string& mid) {
    auto it = mid_slot.find(mid);
    if (it != mid_slot.end()) return it->second;
    int32_t s = (int32_t)mid_slot.size();
    if (s >= msg_window) return -1;  // caller maps to rc=3
    mid_slot.emplace(mid, s);
    mid_order.push_back(mid);
    return s;
  }
  Record& rec_of(const std::string& obs, const std::string& mid) {
    std::string key = obs;
    key.push_back('\0');
    key += mid;
    return records[key];
  }

  bool event(int type, const std::string& obs, double ts, const Payload& pl) {
    constexpr double eps = 1e-9;
    while (ts >= next_decay - eps) {
      emit(OP_DECAY, 0, 0, 0);
      next_decay += decay_interval;
    }
    auto ai_it = peer_index.find(obs);
    if (ai_it == peer_index.end()) return true;
    int32_t ai = ai_it->second;

    switch (type) {
      case EV_GRAFT:
      case EV_PRUNE: {
        int32_t bi = peer_of(pl.peer), ci = topic_of(pl.topic);
        if (bi >= 0 && ci >= 0)
          emit(type == EV_GRAFT ? OP_GRAFT : OP_PRUNE, ai, bi, ci);
        break;
      }
      case EV_JOIN: {
        int32_t ci = topic_of(pl.topic);
        if (ci >= 0) emit(OP_JOIN, ai, -1, ci);
        break;
      }
      case EV_LEAVE: {
        int32_t ci = topic_of(pl.topic);
        if (ci >= 0) emit(OP_LEAVE, ai, -1, ci);
        break;
      }
      case EV_ADD_PEER: {
        int32_t bi = peer_of(pl.peer);
        if (bi >= 0) emit(OP_CONNECT, ai, bi, -1);
        break;
      }
      case EV_REMOVE_PEER: {
        int32_t bi = peer_of(pl.peer);
        if (bi >= 0) emit(OP_DISCONNECT, ai, bi, -1);
        break;
      }
      case EV_PUBLISH_MESSAGE: {
        int32_t ci = topic_of(pl.topic);
        if (ci < 0 || pl.mid.empty()) break;
        int32_t sl = slot_of(pl.mid.str());
        if (sl < 0) return false;
        emit(OP_PUBLISH, ai, sl, ci);
        break;
      }
      case EV_DELIVER_MESSAGE: {
        int32_t ci = topic_of(pl.topic);
        if (ci < 0 || pl.mid.empty()) break;
        std::string mid = pl.mid.str();
        int32_t sl = slot_of(mid);
        if (sl < 0) return false;
        std::string rf = pl.peer.empty() ? std::string() : pl.peer.str();
        // raw score hook gated on received_from != observer (trace/bus.py)
        if (!rf.empty() && rf != obs) {
          int32_t bi = peer_of(pl.peer);
          if (bi >= 0) emit(OP_FIRST, ai, bi, ci);
          Record& r = rec_of(obs, mid);
          if (r.status == ST_UNKNOWN) {
            r.status = ST_VALID;
            r.validated = ts;
            for (const auto& p : r.peers) {
              if (p != rf) {
                auto it = peer_index.find(p);
                if (it != peer_index.end()) emit(OP_DUP, ai, it->second, ci);
              }
            }
          }
        }
        emit(OP_DELIVER, ai, sl, ci);
        break;
      }
      case EV_DUPLICATE_MESSAGE: {
        int32_t ci = topic_of(pl.topic);
        if (ci < 0 || pl.mid.empty() || pl.peer.empty()) break;
        std::string rf = pl.peer.str();
        if (rf == obs) break;
        Record& r = rec_of(obs, pl.mid.str());
        bool seen = false;
        for (const auto& p : r.peers) if (p == rf) { seen = true; break; }
        if (seen) break;
        if (r.status == ST_UNKNOWN) {
          r.peers.push_back(rf);
        } else if (r.status == ST_VALID) {
          r.peers.push_back(rf);
          if (ts - r.validated <= dup_window[ci]) {
            int32_t bi = peer_of(pl.peer);
            if (bi >= 0) emit(OP_DUP, ai, bi, ci);
          }
        } else if (r.status == ST_INVALID) {
          int32_t bi = peer_of(pl.peer);
          if (bi >= 0) emit(OP_INVALID, ai, bi, ci);
        }
        break;
      }
      case EV_REJECT_MESSAGE: {
        int32_t ci = topic_of(pl.topic);
        if (ci < 0 || pl.mid.empty() || pl.peer.empty()) break;
        std::string rf = pl.peer.str();
        if (rf == obs) break;
        std::string reason = pl.reason.empty() ? std::string() : pl.reason.str();
        int32_t bi = peer_of(pl.peer);
        if (is_sig_reject(reason)) {
          if (bi >= 0) emit(OP_INVALID, ai, bi, ci);
          break;
        }
        if (is_silent_reject(reason)) break;
        Record& r = rec_of(obs, pl.mid.str());
        if (r.status != ST_UNKNOWN) break;
        if (reason == "validation throttled") {
          r.status = ST_THROTTLED;
          r.peers.clear();
        } else if (reason == "validation ignored") {
          r.status = ST_IGNORED;
          r.peers.clear();
        } else {
          r.status = ST_INVALID;
          if (bi >= 0) emit(OP_INVALID, ai, bi, ci);
          for (const auto& p : r.peers) {
            auto it = peer_index.find(p);
            if (it != peer_index.end()) emit(OP_INVALID, ai, it->second, ci);
          }
          r.peers.clear();
        }
        break;
      }
      default:
        break;  // RPC meta events carry no replayable state
    }
    return true;
  }
};

// blob format: n records of (uint32 LE length + raw bytes) — binary-safe
// for peer ids that are raw multihashes (pb/codec.py decodes them with
// surrogateescape; the Python side re-encodes byte-preserving)
void split_blob(const char* blob, long n, std::unordered_map<std::string, int32_t>* out) {
  const char* p = blob;
  for (long i = 0; i < n; i++) {
    uint32_t l;
    memcpy(&l, p, 4);
    p += 4;
    out->emplace(std::string(p, l), (int32_t)i);
    p += l;
  }
}

}  // namespace

extern "C" {

// Parse a uvarint-delimited TraceEvent stream and tensorize it.
// peers_blob / topics_blob: n NUL-terminated strings, index = position.
// Returns 0 on success; fills *out (malloc'd interleaved int32 op,a,b,c),
// *out_events (number of ops), *mids (malloc'd NUL-joined message ids in
// slot order), *n_mids. Caller frees via trace_codec_free.
int trace_codec_tensorize(
    const uint8_t* buf, long len,
    const char* peers_blob, long n_peers,
    const char* topics_blob, long n_topics,
    const double* dup_window, double decay_interval,
    double t_end, int has_t_end, long msg_window,
    int32_t** out, long* out_events,
    char** mids, long* n_mids) {
  Tensorizer tz;
  split_blob(peers_blob, n_peers, &tz.peer_index);
  split_blob(topics_blob, n_topics, &tz.topic_index);
  tz.dup_window = dup_window;
  tz.decay_interval = decay_interval;
  tz.next_decay = decay_interval;
  tz.msg_window = msg_window;

  size_t pos = 0;
  while (pos < (size_t)len) {
    uint64_t elen;
    if (!read_uvarint(buf, len, &pos, &elen)) return 2;
    if (elen > (size_t)len - pos) return 2;
    const uint8_t* ep = buf + pos;
    pos += elen;

    int type = -1;
    double ts = 0.0;
    std::string obs;
    Slice payload;
    bool ok = walk_fields(ep, elen, [&](uint32_t f, uint32_t w, uint64_t v, Slice s) {
      if (f == 1 && w == 0) type = (int)v;
      else if (f == 2 && w == 2) obs = s.str();
      else if (f == 3 && w == 0) ts = (double)v / 1e9;
      else if (f >= 4 && f <= 16 && w == 2) payload = s;
    });
    if (!ok) return 2;  // malformed event body -> loud error, like the
                        // Python codec's _iter_fields raising
    if (type < 0) continue;
    Payload pl;
    if (!payload.empty() && !parse_payload(type, payload, &pl)) return 2;
    if (!tz.event(type, obs, ts, pl)) return 3;
  }

  if (has_t_end) {
    constexpr double eps = 1e-9;
    while (tz.next_decay <= t_end + eps) {
      tz.emit(OP_DECAY, 0, 0, 0);
      tz.next_decay += decay_interval;
    }
  }
  if (tz.ops.empty()) tz.emit(OP_NOP, 0, 0, 0);

  long n_ops = (long)(tz.ops.size() / 4);
  int32_t* arr = (int32_t*)malloc(tz.ops.size() * sizeof(int32_t));
  memcpy(arr, tz.ops.data(), tz.ops.size() * sizeof(int32_t));
  *out = arr;
  *out_events = n_ops;

  // message ids are binary (default id = from||seqno, midgen.py), so the
  // slot-order blob is length-prefixed: uint32 LE length + raw bytes each
  size_t mlen = 0;
  for (const auto& m : tz.mid_order) mlen += 4 + m.size();
  char* mblob = (char*)malloc(mlen ? mlen : 1);
  char* mp = mblob;
  for (const auto& m : tz.mid_order) {
    uint32_t l = (uint32_t)m.size();
    memcpy(mp, &l, 4);
    mp += 4;
    memcpy(mp, m.data(), m.size());
    mp += m.size();
  }
  *mids = mblob;
  *n_mids = (long)tz.mid_order.size();
  return 0;
}

void trace_codec_free(void* p) { free(p); }

// Encode helper: frame a pre-encoded TraceEvent blob stream is trivial in
// Python; the native side only ships the parse/tensorize hot path.

// Health-row NDJSON encoder (sim/telemetry.py hot sink path): format a
// whole chunk's [n_rows, n_cols] float64 row matrix as one NDJSON blob in
// a single call — the per-row Python dict + json.dumps overhead is the
// encoder-side cost the streaming plane removes. names_blob uses the
// split_blob convention (uint32 LE length + raw bytes per column name);
// is_int marks columns printed as integers. Doubles print as %.17g
// (round-trips every finite double bit-exactly through a JSON parser);
// non-finite values print as null (NaN is not JSON — a degraded row must
// stay machine-readable). Returns 0; caller frees *out via
// trace_codec_free.
int trace_codec_health_json(const double* vals, long n_rows, long n_cols,
                            const char* names_blob, long names_len,
                            const unsigned char* is_int,
                            char** out, long* out_len) {
  std::vector<std::string> names;
  names.reserve(n_cols);
  {
    const char* p = names_blob;
    (void)names_len;
    for (long i = 0; i < n_cols; i++) {
      uint32_t l;
      memcpy(&l, p, 4);
      p += 4;
      names.emplace_back(p, l);
      p += l;
    }
  }
  // pre-render the '"name":' fragments once; rows reuse them
  std::vector<std::string> keys;
  keys.reserve(n_cols);
  for (long c = 0; c < n_cols; c++)
    keys.push_back(std::string(c ? ",\"" : "{\"kind\":\"health\",\"")
                   + names[c] + "\":");
  std::string buf;
  buf.reserve((size_t)n_rows * n_cols * 24 + 64);
  char num[40];
  for (long r = 0; r < n_rows; r++) {
    const double* row = vals + r * n_cols;
    for (long c = 0; c < n_cols; c++) {
      buf += keys[c];
      double v = row[c];
      if (!std::isfinite(v)) {
        buf += "null";
      } else if (is_int[c]) {
        snprintf(num, sizeof num, "%lld", (long long)v);
        buf += num;
      } else {
        snprintf(num, sizeof num, "%.17g", v);
        buf += num;
      }
    }
    buf += "}\n";
  }
  char* p = (char*)malloc(buf.size() ? buf.size() : 1);
  memcpy(p, buf.data(), buf.size());
  *out = p;
  *out_len = (long)buf.size();
  return 0;
}

}  // extern "C"
