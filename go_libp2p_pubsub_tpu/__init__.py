"""go_libp2p_pubsub_tpu: a TPU-native pubsub framework.

A from-scratch re-design of the capabilities of go-libp2p-pubsub
(floodsub / randomsub / gossipsub v1.1 with peer scoring) built in two halves:

- a **functional core**: pure-Python deterministic discrete-event runtime with
  the full application API (Join/Subscribe/Publish/validators/tracing), used
  node-by-node for correctness and API parity with the reference
  (see /root/reference: pubsub.go, gossipsub.go, score.go, ...);
- a **batched simulation engine** (`sim/`, `ops/`, `parallel/`): the same
  router semantics vectorized over all N peers as pytrees of JAX arrays,
  stepped under jit/shard_map on TPU meshes — the performance product
  (heartbeat + scoring + propagation as batched sparse-graph computation).

Nothing here is a port: the reference is single-node, goroutine-based Go; this
package is array-programming-first, with a virtual clock, fixed-capacity
padded state, and XLA collectives where the reference had libp2p streams.
"""

__version__ = "0.1.0"

from .core.params import (  # noqa: F401
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    score_parameter_decay,
)
